"""nn.functional geometry/resampling ops vs torch — the classic
convention bug nests (align_corners, padding modes, NCHW layouts,
normalized grids). torch.nn.functional is an independent implementation
of the same reference semantics (paddle mirrors torch here), so
disagreement means a real convention bug.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

F = paddle.nn.functional
RTOL, ATOL = 1e-3, 1e-3


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def rand(*s, seed=0):
    return np.random.RandomState(seed).randn(*s).astype(np.float32)


class TestInterpolate:
    @pytest.mark.parametrize("mode,align", [
        ("nearest", False),
        ("bilinear", False), ("bilinear", True),
        ("bicubic", False), ("bicubic", True),
    ])
    def test_upsample_2d_modes(self, mode, align):
        x = rand(2, 3, 5, 7, seed=1)
        kw = {} if mode == "nearest" else {"align_corners": align}
        got = _np(F.interpolate(_t(x), size=(10, 13), mode=mode, **kw))
        want = TF.interpolate(torch.from_numpy(x), size=(10, 13),
                              mode=mode, **kw).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL,
                                   err_msg=f"{mode} align={align}")

    @pytest.mark.parametrize("align", [False, True])
    def test_downsample_bilinear(self, align):
        x = rand(1, 2, 12, 16, seed=2)
        got = _np(F.interpolate(_t(x), size=(5, 7), mode="bilinear",
                                align_corners=align))
        want = TF.interpolate(torch.from_numpy(x), size=(5, 7),
                              mode="bilinear", align_corners=align).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_scale_factor(self):
        x = rand(1, 2, 6, 6, seed=3)
        got = _np(F.interpolate(_t(x), scale_factor=2.0, mode="nearest"))
        want = TF.interpolate(torch.from_numpy(x),
                              scale_factor=2.0, mode="nearest").numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_linear_1d_and_trilinear_3d(self):
        x1 = rand(2, 3, 9, seed=4)
        got = _np(F.interpolate(_t(x1), size=(15,), mode="linear",
                                align_corners=True))
        want = TF.interpolate(torch.from_numpy(x1), size=15,
                              mode="linear", align_corners=True).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        x3 = rand(1, 2, 4, 5, 6, seed=5)
        got = _np(F.interpolate(_t(x3), size=(8, 7, 9), mode="trilinear",
                                align_corners=False))
        want = TF.interpolate(torch.from_numpy(x3), size=(8, 7, 9),
                              mode="trilinear",
                              align_corners=False).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


class TestGridSample:
    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
    @pytest.mark.parametrize("align", [False, True])
    def test_grid_sample_full_matrix(self, mode, pad, align):
        x = rand(2, 3, 6, 7, seed=6)
        grid = (np.random.RandomState(7).rand(2, 5, 4, 2).astype(
            np.float32) * 2.4 - 1.2)       # includes out-of-bounds
        got = _np(F.grid_sample(_t(x), _t(grid), mode=mode,
                                padding_mode=pad, align_corners=align))
        want = TF.grid_sample(torch.from_numpy(x),
                              torch.from_numpy(grid), mode=mode,
                              padding_mode=pad,
                              align_corners=align).numpy()
        np.testing.assert_allclose(
            got, want, rtol=RTOL, atol=ATOL,
            err_msg=f"{mode}/{pad}/align={align}")

    def test_affine_grid_matches_torch(self):
        theta = np.array([[[0.8, 0.1, 0.2], [-0.1, 0.9, -0.3]]],
                         np.float32)
        for align in (False, True):
            got = _np(F.affine_grid(_t(theta), [1, 3, 5, 6],
                                    align_corners=align))
            want = TF.affine_grid(torch.from_numpy(theta), [1, 3, 5, 6],
                                  align_corners=align).numpy()
            np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL,
                                       err_msg=f"align={align}")


class TestPadAndShuffle:
    @pytest.mark.parametrize("mode", ["reflect", "replicate", "circular"])
    def test_pad_modes_4d(self, mode):
        x = rand(2, 3, 5, 6, seed=8)
        pads = [1, 2, 2, 1]
        got = _np(F.pad(_t(x), pads, mode=mode))
        want = TF.pad(torch.from_numpy(x), pads, mode=mode).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_pad_constant_value(self):
        x = rand(2, 3, 4, 4, seed=9)
        got = _np(F.pad(_t(x), [1, 1, 2, 0], mode="constant", value=3.5))
        want = TF.pad(torch.from_numpy(x), [1, 1, 2, 0],
                      mode="constant", value=3.5).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_pixel_shuffle_roundtrip_and_torch(self):
        x = rand(2, 8, 3, 4, seed=10)
        got = _np(F.pixel_shuffle(_t(x), 2))
        want = TF.pixel_shuffle(torch.from_numpy(x), 2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        back = _np(F.pixel_unshuffle(_t(got), 2))
        np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)

    def test_unfold_fold_roundtrip(self):
        x = rand(1, 2, 6, 6, seed=11)
        u = F.unfold(_t(x), kernel_sizes=3, strides=3)
        want_u = TF.unfold(torch.from_numpy(x), 3, stride=3).numpy()
        np.testing.assert_allclose(_np(u), want_u, rtol=RTOL, atol=ATOL)
        back = _np(F.fold(u, output_sizes=[6, 6], kernel_sizes=3,
                          strides=3))
        np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)


class TestPooling:
    @pytest.mark.parametrize("ceil", [False, True])
    def test_max_pool2d_ceil_mode(self, ceil):
        x = rand(2, 3, 7, 9, seed=12)
        got = _np(F.max_pool2d(_t(x), kernel_size=3, stride=2,
                               padding=1, ceil_mode=ceil))
        want = TF.max_pool2d(torch.from_numpy(x), 3, stride=2, padding=1,
                             ceil_mode=ceil).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("exclusive", [True, False])
    def test_avg_pool2d_count_include_pad(self, exclusive):
        # paddle exclusive=True == torch count_include_pad=False
        x = rand(1, 2, 6, 6, seed=13)
        got = _np(F.avg_pool2d(_t(x), kernel_size=3, stride=2, padding=1,
                               exclusive=exclusive))
        want = TF.avg_pool2d(torch.from_numpy(x), 3, stride=2, padding=1,
                             count_include_pad=not exclusive).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_adaptive_pools_uneven(self):
        # 7 -> 3 forces uneven windows: the classic adaptive-pool bug
        x = rand(2, 3, 7, 7, seed=14)
        got = _np(F.adaptive_avg_pool2d(_t(x), output_size=3))
        want = TF.adaptive_avg_pool2d(torch.from_numpy(x), 3).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        got = _np(F.adaptive_max_pool2d(_t(x), output_size=3))
        want = TF.adaptive_max_pool2d(torch.from_numpy(x), 3).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_max_pool1d_3d(self):
        x1 = rand(2, 3, 11, seed=15)
        got = _np(F.max_pool1d(_t(x1), kernel_size=2, stride=2))
        want = TF.max_pool1d(torch.from_numpy(x1), 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        x3 = rand(1, 2, 4, 6, 6, seed=16)
        got = _np(F.max_pool3d(_t(x3), kernel_size=2, stride=2))
        want = TF.max_pool3d(torch.from_numpy(x3), 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


class TestConvs:
    @pytest.mark.parametrize("groups", [1, 2])
    @pytest.mark.parametrize("dilation", [1, 2])
    def test_conv2d_groups_dilation(self, groups, dilation):
        x = rand(2, 4, 9, 9, seed=17)
        w = rand(6, 4 // groups, 3, 3, seed=18) * 0.2
        b = rand(6, seed=19)
        got = _np(F.conv2d(_t(x), _t(w), _t(b), stride=2, padding=2,
                           dilation=dilation, groups=groups))
        want = TF.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                         torch.from_numpy(b), stride=2, padding=2,
                         dilation=dilation, groups=groups).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)

    @pytest.mark.parametrize("output_padding", [0, 1])
    def test_conv2d_transpose_output_padding(self, output_padding):
        x = rand(1, 3, 5, 5, seed=20)
        w = rand(3, 4, 3, 3, seed=21) * 0.2
        got = _np(F.conv2d_transpose(_t(x), _t(w), stride=2, padding=1,
                                     output_padding=output_padding))
        want = TF.conv_transpose2d(torch.from_numpy(x),
                                   torch.from_numpy(w), stride=2,
                                   padding=1,
                                   output_padding=output_padding).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)

    def test_conv1d_and_3d(self):
        x1 = rand(2, 3, 12, seed=22)
        w1 = rand(5, 3, 4, seed=23) * 0.2
        got = _np(F.conv1d(_t(x1), _t(w1), stride=2, padding=1))
        want = TF.conv1d(torch.from_numpy(x1), torch.from_numpy(w1),
                         stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)
        x3 = rand(1, 2, 5, 6, 6, seed=24)
        w3 = rand(4, 2, 3, 3, 3, seed=25) * 0.2
        got = _np(F.conv3d(_t(x3), _t(w3), padding=1))
        want = TF.conv3d(torch.from_numpy(x3), torch.from_numpy(w3),
                         padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)


class TestNorms2:
    def test_batch_norm_train_vs_eval(self):
        x = rand(4, 3, 5, 5, seed=26)
        w = rand(3, seed=27) * 0.5 + 1.0
        b = rand(3, seed=28) * 0.1
        rm = np.zeros(3, np.float32)
        rv = np.ones(3, np.float32)
        for training in (True, False):
            got = _np(F.batch_norm(_t(x), _t(rm.copy()), _t(rv.copy()),
                                   weight=_t(w), bias=_t(b),
                                   training=training, momentum=0.9,
                                   epsilon=1e-5))
            want = TF.batch_norm(torch.from_numpy(x),
                                 torch.from_numpy(rm.copy()),
                                 torch.from_numpy(rv.copy()),
                                 torch.from_numpy(w), torch.from_numpy(b),
                                 training=training, momentum=0.1,
                                 eps=1e-5).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3,
                                       err_msg=f"training={training}")

    def test_layer_group_instance_norm(self):
        x = rand(2, 4, 6, 6, seed=29)
        w4 = rand(4, seed=30) + 1.0
        b4 = rand(4, seed=31) * 0.1
        got = _np(F.group_norm(_t(x), num_groups=2, weight=_t(w4),
                               bias=_t(b4), epsilon=1e-5))
        want = TF.group_norm(torch.from_numpy(x), 2,
                             torch.from_numpy(w4), torch.from_numpy(b4),
                             eps=1e-5).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
        got = _np(F.instance_norm(_t(x), weight=_t(w4), bias=_t(b4),
                                  eps=1e-5))
        want = TF.instance_norm(torch.from_numpy(x),
                                weight=torch.from_numpy(w4),
                                bias=torch.from_numpy(b4),
                                eps=1e-5).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_local_response_norm(self):
        x = rand(2, 6, 5, 5, seed=32)
        got = _np(F.local_response_norm(_t(x), size=3, alpha=1e-3,
                                        beta=0.8, k=1.2))
        want = TF.local_response_norm(torch.from_numpy(x), 3, alpha=1e-3,
                                      beta=0.8, k=1.2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestLosses:
    def test_cross_entropy_label_smoothing_and_weights(self):
        logits = rand(6, 5, seed=33)
        labels = np.array([0, 2, 4, 1, 3, 2], np.int64)
        w = (np.abs(rand(5, seed=34)) + 0.5).astype(np.float32)
        got = _np(F.cross_entropy(_t(logits), _t(labels), weight=_t(w),
                                  reduction="mean"))
        want = TF.cross_entropy(torch.from_numpy(logits),
                                torch.from_numpy(labels),
                                weight=torch.from_numpy(w)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
        # soft labels
        soft = np.abs(rand(6, 5, seed=35)).astype(np.float32)
        soft /= soft.sum(1, keepdims=True)
        got = _np(F.cross_entropy(_t(logits), _t(soft), soft_label=True))
        want = TF.cross_entropy(torch.from_numpy(logits),
                                torch.from_numpy(soft)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_kl_div_reductions(self, reduction):
        p = np.abs(rand(4, 5, seed=36)) + 0.1
        p /= p.sum(1, keepdims=True)
        q = np.abs(rand(4, 5, seed=37)) + 0.1
        q /= q.sum(1, keepdims=True)
        logq = np.log(q).astype(np.float32)
        got = _np(F.kl_div(_t(logq), _t(p.astype(np.float32)),
                           reduction=reduction))
        want = TF.kl_div(torch.from_numpy(logq), torch.from_numpy(
            p.astype(np.float32)), reduction=reduction).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_ctc_loss_matches_torch(self):
        T, B, C = 12, 2, 5           # time, batch, classes (0 = blank)
        logits = rand(T, B, C, seed=38)
        logp = torch.from_numpy(logits).log_softmax(-1)
        labels = np.array([[1, 2, 3], [2, 4, 0]], np.int64)
        in_lens = np.array([12, 10], np.int64)
        lbl_lens = np.array([3, 2], np.int64)
        want = TF.ctc_loss(logp, torch.from_numpy(labels),
                           torch.from_numpy(in_lens),
                           torch.from_numpy(lbl_lens), blank=0,
                           reduction="none").numpy()
        got = _np(F.ctc_loss(_t(logits), _t(labels), _t(in_lens),
                             _t(lbl_lens), blank=0, reduction="none"))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_margin_and_bce(self):
        a, b, c = rand(4, 6, seed=39), rand(4, 6, seed=40), rand(4, 6,
                                                                 seed=41)
        got = _np(F.triplet_margin_loss(_t(a), _t(b), _t(c), margin=0.5))
        want = TF.triplet_margin_loss(torch.from_numpy(a),
                                      torch.from_numpy(b),
                                      torch.from_numpy(c),
                                      margin=0.5).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
        logits = rand(5, 3, seed=42)
        tgt = (np.abs(rand(5, 3, seed=43)) < 0.7).astype(np.float32)
        got = _np(F.binary_cross_entropy_with_logits(_t(logits), _t(tgt)))
        want = TF.binary_cross_entropy_with_logits(
            torch.from_numpy(logits), torch.from_numpy(tgt)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_smooth_l1_huber_delta(self):
        x, y = rand(6, seed=44), rand(6, seed=45)
        got = _np(F.smooth_l1_loss(_t(x), _t(y), delta=2.0))
        want = TF.huber_loss(torch.from_numpy(x), torch.from_numpy(y),
                             delta=2.0).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestActivationsEmbedding:
    def test_gelu_exact_vs_tanh(self):
        x = rand(100, seed=46) * 3
        got = _np(F.gelu(_t(x), approximate=False))
        want = TF.gelu(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
        got = _np(F.gelu(_t(x), approximate=True))
        want = TF.gelu(torch.from_numpy(x), approximate="tanh").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_embedding_padding_idx_zero_vector(self):
        # PADDLE semantics (reference input.py:155): the padding id's
        # output is ALL-ZERO in forward — torch instead returns the row
        # and only zeroes its gradient. Non-padding rows match torch.
        w = rand(10, 4, seed=47)
        ids = np.array([[1, 2, 3], [3, 2, 9]], np.int64)
        got = _np(F.embedding(_t(ids), _t(w), padding_idx=2))
        want = TF.embedding(torch.from_numpy(ids),
                            torch.from_numpy(w)).numpy()
        pad_mask = ids == 2
        np.testing.assert_allclose(got[pad_mask], 0.0)
        np.testing.assert_allclose(got[~pad_mask], want[~pad_mask],
                                   rtol=1e-4, atol=1e-4)

    def test_softmax_log_softmax_axis(self):
        x = rand(3, 4, 5, seed=48)
        for ax in (0, 1, -1):
            np.testing.assert_allclose(
                _np(F.softmax(_t(x), axis=ax)),
                TF.softmax(torch.from_numpy(x), dim=ax).numpy(),
                rtol=1e-3, atol=1e-3)
            np.testing.assert_allclose(
                _np(F.log_softmax(_t(x), axis=ax)),
                TF.log_softmax(torch.from_numpy(x), dim=ax).numpy(),
                rtol=1e-3, atol=1e-3)


class TestRNNsVsTorch:
    """LSTM/GRU/SimpleRNN numerics with identical weights — gate order
    and bias-pair conventions are where ports silently diverge."""

    def _copy_weights(self, pd_rnn, th_rnn):
        """paddle 'rnns.{l}[.rnn_fw|.rnn_bw].cell.{kind}' maps onto torch
        '{kind}_l{l}[_reverse]'."""
        import torch as th

        pd = {k: p for k, p in pd_rnn.named_parameters()}
        for name, par in th_rnn.named_parameters():
            kind, rest = name.split("_l", 1) if "_l" in name else (name, "")
            layer = rest.split("_")[0]
            rev = rest.endswith("_reverse")
            mid = ".rnn_bw" if rev else (
                ".rnn_fw" if any("rnn_fw" in k for k in pd) else "")
            pd_name = f"rnns.{layer}{mid}.cell.{kind}"
            assert pd_name in pd, (name, pd_name, list(pd))
            v = _np(pd[pd_name])
            with th.no_grad():
                par.copy_(th.from_numpy(np.ascontiguousarray(v)))

    @pytest.mark.parametrize("cls", ["LSTM", "GRU", "SimpleRNN"])
    def test_single_layer_forward(self, cls):
        B, T, I, H = 2, 5, 4, 3
        x = rand(B, T, I, seed=50)
        pd_rnn = getattr(paddle.nn, cls)(I, H)
        th_cls = {"LSTM": torch.nn.LSTM, "GRU": torch.nn.GRU,
                  "SimpleRNN": torch.nn.RNN}[cls]
        th_rnn = th_cls(I, H, batch_first=True)
        self._copy_weights(pd_rnn, th_rnn)
        got, _ = pd_rnn(_t(x))
        want, _ = th_rnn(torch.from_numpy(x))
        np.testing.assert_allclose(_np(got), want.detach().numpy(),
                                   rtol=1e-3, atol=1e-3)

    def test_bidirectional_lstm(self):
        B, T, I, H = 2, 6, 3, 4
        x = rand(B, T, I, seed=51)
        pd_rnn = paddle.nn.LSTM(I, H, direction="bidirect")
        th_rnn = torch.nn.LSTM(I, H, batch_first=True, bidirectional=True)
        self._copy_weights(pd_rnn, th_rnn)
        got, _ = pd_rnn(_t(x))
        want, _ = th_rnn(torch.from_numpy(x))
        np.testing.assert_allclose(_np(got), want.detach().numpy(),
                                   rtol=1e-3, atol=1e-3)

    def test_two_layer_gru_states(self):
        B, T, I, H = 2, 4, 3, 3
        x = rand(B, T, I, seed=52)
        pd_rnn = paddle.nn.GRU(I, H, num_layers=2)
        th_rnn = torch.nn.GRU(I, H, num_layers=2, batch_first=True)
        self._copy_weights(pd_rnn, th_rnn)
        got, h = pd_rnn(_t(x))
        want, h_t = th_rnn(torch.from_numpy(x))
        np.testing.assert_allclose(_np(got), want.detach().numpy(),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(_np(h), h_t.detach().numpy(),
                                   rtol=1e-3, atol=1e-3)


class TestAttentionVsTorch:
    """MultiHeadAttention with identical in/out projection weights —
    mask conventions and head splitting are the classic divergences.
    NOTE paddle masks are ADDITIVE (or bool keep=True); torch attn_mask
    bool means True=BLOCKED. The test covers both forms."""

    def _mha_pair(self, E=8, H=2, seed=60):
        import torch as th

        pd = paddle.nn.MultiHeadAttention(E, H)
        t = th.nn.MultiheadAttention(E, H, batch_first=True)
        rng = np.random.RandomState(seed)
        wq, wk, wv = (rng.randn(E, E).astype(np.float32) * 0.3
                      for _ in range(3))
        wo = rng.randn(E, E).astype(np.float32) * 0.3
        bq, bk, bv, bo = (rng.randn(E).astype(np.float32) * 0.1
                          for _ in range(4))
        # paddle: per-proj Linear [in,out]; torch: packed [3E, E] (out,in)
        for name, w, b in (("q_proj", wq, bq), ("k_proj", wk, bk),
                           ("v_proj", wv, bv), ("out_proj", wo, bo)):
            getattr(pd, name).weight.set_value(paddle.to_tensor(w))
            getattr(pd, name).bias.set_value(paddle.to_tensor(b))
        with th.no_grad():
            t.in_proj_weight.copy_(th.from_numpy(
                np.concatenate([wq.T, wk.T, wv.T], 0)))
            t.in_proj_bias.copy_(th.from_numpy(
                np.concatenate([bq, bk, bv], 0)))
            t.out_proj.weight.copy_(th.from_numpy(wo.T))
            t.out_proj.bias.copy_(th.from_numpy(bo))
        return pd, t

    def test_self_attention_no_mask(self):
        pd, t = self._mha_pair()
        x = rand(2, 5, 8, seed=61)
        got = _np(pd(_t(x), _t(x), _t(x)))
        want, _ = t(torch.from_numpy(x), torch.from_numpy(x),
                    torch.from_numpy(x))
        np.testing.assert_allclose(got, want.detach().numpy(), rtol=1e-3,
                                   atol=1e-3)

    def test_additive_mask_matches_torch_float_mask(self):
        pd, t = self._mha_pair(seed=62)
        x = rand(2, 4, 8, seed=63)
        # causal additive mask
        m = np.triu(np.full((4, 4), -1e9, np.float32), k=1)
        got = _np(pd(_t(x), _t(x), _t(x), attn_mask=_t(m)))
        want, _ = t(torch.from_numpy(x), torch.from_numpy(x),
                    torch.from_numpy(x),
                    attn_mask=torch.from_numpy(m))
        np.testing.assert_allclose(got, want.detach().numpy(), rtol=1e-3,
                                   atol=1e-3)

    def test_cross_attention_different_kv_len(self):
        pd, t = self._mha_pair(seed=64)
        q = rand(2, 3, 8, seed=65)
        kv = rand(2, 6, 8, seed=66)
        got = _np(pd(_t(q), _t(kv), _t(kv)))
        want, _ = t(torch.from_numpy(q), torch.from_numpy(kv),
                    torch.from_numpy(kv))
        np.testing.assert_allclose(got, want.detach().numpy(), rtol=1e-3,
                                   atol=1e-3)


class TestActivationZoo:
    """One sweep over the activation family vs torch."""

    CASES = [
        ("relu6", {}, "relu6", {}),
        ("selu", {}, "selu", {}),
        ("celu", {"alpha": 1.3}, "celu", {"alpha": 1.3}),
        ("elu", {"alpha": 0.7}, "elu", {"alpha": 0.7}),
        ("mish", {}, "mish", {}),
        ("hardswish", {}, "hardswish", {}),
        ("hardsigmoid", {}, "hardsigmoid", {}),
        ("softplus", {"beta": 2.0}, "softplus", {"beta": 2.0}),
        ("softsign", {}, "softsign", {}),
        ("tanhshrink", {}, "tanhshrink", {}),
        ("hardtanh", {"min": -0.6, "max": 0.4}, "hardtanh",
         {"min_val": -0.6, "max_val": 0.4}),
        ("leaky_relu", {"negative_slope": 0.2}, "leaky_relu",
         {"negative_slope": 0.2}),
        ("log_sigmoid", {}, "logsigmoid", {}),
        ("silu", {}, "silu", {}),
    ]

    @pytest.mark.parametrize("pd_name,pd_kw,th_name,th_kw", CASES)
    def test_matches_torch(self, pd_name, pd_kw, th_name, th_kw):
        x = rand(64, seed=70) * 3
        got = _np(getattr(F, pd_name)(_t(x), **pd_kw))
        want = getattr(TF, th_name)(torch.from_numpy(x), **th_kw).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=pd_name)

    def test_softshrink_hardshrink_thresholded(self):
        x = rand(32, seed=71)
        np.testing.assert_allclose(
            _np(F.softshrink(_t(x), threshold=0.3)),
            TF.softshrink(torch.from_numpy(x), lambd=0.3).numpy(),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _np(F.hardshrink(_t(x), threshold=0.3)),
            TF.hardshrink(torch.from_numpy(x), lambd=0.3).numpy(),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _np(F.thresholded_relu(_t(x), threshold=0.2)),
            TF.threshold(torch.from_numpy(x), 0.2, 0.0).numpy(),
            rtol=1e-5, atol=1e-6)


class TestInterpolateAreaAndAlignNearest:
    def test_area_is_box_mean(self):
        # [0,0,0,100] downsampled 4x by area must give the block MEAN
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 3, 3] = 100.0
        got = _np(F.interpolate(_t(x), size=(1, 1), mode="area"))
        np.testing.assert_allclose(got, [[[[100.0 / 16]]]], rtol=1e-6)
        # and matches torch adaptive/area semantics on random input
        y = rand(2, 3, 9, 12, seed=80)
        got = _np(F.interpolate(_t(y), size=(3, 4), mode="area"))
        want = TF.interpolate(torch.from_numpy(y), size=(3, 4),
                              mode="area").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_area_uneven_windows(self):
        y = rand(1, 2, 7, 5, seed=81)
        got = _np(F.interpolate(_t(y), size=(3, 2), mode="area"))
        want = TF.interpolate(torch.from_numpy(y), size=(3, 2),
                              mode="area").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_nearest_align_corners_true(self):
        # paddle nearest_interp with align_corners: round(i*(in-1)/(out-1))
        x = np.arange(5, dtype=np.float32).reshape(1, 1, 1, 5)
        got = _np(F.interpolate(_t(x), size=(1, 3), mode="nearest",
                                align_corners=True))
        np.testing.assert_allclose(got.ravel(), [0.0, 2.0, 4.0])
