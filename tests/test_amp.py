"""AMP tests: autocast dtype policy, O2 decorate, GradScaler state machine,
nan/inf sentry, operator stats (reference test analogs:
test/amp/test_amp_api.py, test_grad_scaler.py)."""
import numpy as np
import pytest

import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import amp, nn
from paddle_tpu.amp.debugging import (DebugMode, TensorCheckerConfig,
                                      collect_operator_stats,
                                      disable_tensor_checker,
                                      enable_tensor_checker)


class TestAutoCast:
    def test_o1_white_op_casts(self):
        x = paddle.ones([4, 4])
        y = paddle.ones([4, 4])
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            out = paddle.matmul(x, y)
        assert out.dtype == jnp.bfloat16
        # outside the scope: fp32 again
        out2 = paddle.matmul(x, y)
        assert out2.dtype == jnp.float32

    def test_o1_black_op_stays_fp32(self):
        x = paddle.ones([4, 4], dtype="bfloat16")
        with amp.auto_cast(level="O1"):
            out = paddle.exp(x)
        assert out.dtype == jnp.float32

    def test_o1_gray_op_keeps_dtype(self):
        x = paddle.ones([4])
        with amp.auto_cast(level="O1"):
            out = x + 1.0
        assert out.dtype == jnp.float32

    def test_custom_lists(self):
        x = paddle.ones([4, 4])
        with amp.auto_cast(level="O1", custom_black_list={"matmul"}):
            out = paddle.matmul(x, x)
        assert out.dtype == jnp.float32

    def test_o2_casts_gray_ops(self):
        x = paddle.ones([4])
        with amp.auto_cast(level="O2"):
            out = paddle.tanh(x)
        assert out.dtype == jnp.bfloat16

    def test_disabled(self):
        x = paddle.ones([4, 4])
        with amp.auto_cast(enable=False):
            out = paddle.matmul(x, x)
        assert out.dtype == jnp.float32

    def test_leaf_grads_keep_param_dtype(self):
        # autocast cast is part of the differentiated function: fp32 params
        # get fp32 grads (master grads) even when compute ran in bf16/fp16
        model = nn.Linear(4, 4)
        x = paddle.ones([2, 4])
        with amp.auto_cast(level="O1", dtype="float16"):
            loss = model(x).mean()
        loss.backward()
        assert model.weight.dtype == jnp.float32
        assert model.weight.grad.dtype == jnp.float32

    def test_fp16_dtype(self):
        x = paddle.ones([4, 4])
        with amp.auto_cast(level="O1", dtype="float16"):
            out = paddle.matmul(x, x)
        assert out.dtype == jnp.float16

    def test_bad_level(self):
        with pytest.raises(ValueError):
            with amp.auto_cast(level="O3"):
                pass

    def test_nested_disable(self):
        x = paddle.ones([4, 4])
        with amp.auto_cast(level="O1"):
            with amp.auto_cast(enable=False):
                out = paddle.matmul(x, x)
            out2 = paddle.matmul(x, x)
        assert out.dtype == jnp.float32   # inner region: AMP off
        assert out2.dtype == jnp.bfloat16  # outer region restored


class TestDecorate:
    def test_o2_casts_params_not_norms(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.LayerNorm(8))
        model = amp.decorate(model, level="O2", dtype="bfloat16")
        assert model[0].weight.dtype == jnp.bfloat16
        assert model[1].weight.dtype == jnp.float32

    def test_o1_no_cast(self):
        model = nn.Linear(8, 8)
        model = amp.decorate(model, level="O1")
        assert model.weight.dtype == jnp.float32

    def test_excluded_layer_instance(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        model = amp.decorate(model, level="O2",
                             excluded_layers=[model[1]])
        assert model[0].weight.dtype == jnp.bfloat16
        assert model[1].weight.dtype == jnp.float32

    def test_excluded_layer_class(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.Embedding(4, 8))
        model = amp.decorate(model, level="O2", excluded_layers=[nn.Embedding])
        assert model[1].weight.dtype == jnp.float32

    def test_with_optimizer(self):
        from paddle_tpu.optimizer import SGD

        model = nn.Linear(8, 8)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())
        model, opt = amp.decorate(model, opt, level="O2")
        assert model.weight.dtype == jnp.bfloat16


class TestGradScaler:
    def _train_once(self, scaler, poison=False):
        from paddle_tpu.optimizer import SGD

        model = nn.Linear(4, 4)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())
        w0 = model.weight.numpy().copy()
        x = paddle.ones([2, 4])
        loss = model(x).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        if poison:
            model.weight.grad = paddle.Tensor(
                np.full((4, 4), np.nan, np.float32))
        scaler.step(opt)
        scaler.update()
        return w0, model.weight.numpy()

    def test_scale_value(self):
        s = amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.ones([2])
        assert float(s.scale(x).sum()) == 256.0

    def test_step_updates(self):
        s = amp.GradScaler(init_loss_scaling=2.0 ** 10)
        w0, w1 = self._train_once(s)
        assert not np.allclose(w0, w1)

    def test_inf_skips_step_and_shrinks_scale(self):
        s = amp.GradScaler(init_loss_scaling=1024.0,
                           decr_every_n_nan_or_inf=1)
        w0, w1 = self._train_once(s, poison=True)
        np.testing.assert_array_equal(w0, w1)  # step skipped
        assert s.get_loss_scaling() == 512.0

    def test_growth(self):
        s = amp.GradScaler(init_loss_scaling=64.0, incr_every_n_steps=2,
                           incr_ratio=2.0)
        self._train_once(s)
        assert s.get_loss_scaling() == 64.0
        self._train_once(s)
        assert s.get_loss_scaling() == 128.0

    def test_double_step_raises(self):
        from paddle_tpu.optimizer import SGD

        s = amp.GradScaler()
        model = nn.Linear(2, 2)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())
        loss = model(paddle.ones([1, 2])).mean()
        s.scale(loss).backward()
        s.step(opt)
        with pytest.raises(RuntimeError):
            s.step(opt)

    def test_unscale_after_step_raises(self):
        from paddle_tpu.optimizer import SGD

        s = amp.GradScaler()
        model = nn.Linear(2, 2)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())
        s.scale(model(paddle.ones([1, 2])).mean()).backward()
        s.step(opt)
        with pytest.raises(RuntimeError, match="unscale_"):
            s.unscale_(opt)

    def test_disabled_passthrough(self):
        s = amp.GradScaler(enable=False)
        x = paddle.ones([2])
        assert s.scale(x) is x
        assert s.state_dict() == {}

    def test_state_dict_roundtrip(self):
        s = amp.GradScaler(init_loss_scaling=777.0)
        st = s.state_dict()
        s2 = amp.GradScaler()
        s2.load_state_dict(st)
        assert s2.get_loss_scaling() == 777.0


class TestDebugging:
    def test_check_nan_inf_flag(self):
        # the PUBLIC flag path alone must arm the sentry
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor([1.0, 0.0])
            with pytest.raises(RuntimeError, match="Nan/Inf"):
                paddle.log(x - 2.0)
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_tensor_checker(self):
        cfg = TensorCheckerConfig(enable=True,
                                  debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT)
        enable_tensor_checker(cfg)
        try:
            x = paddle.to_tensor([-1.0])
            with pytest.raises(RuntimeError, match="nan_inf"):
                paddle.sqrt(x)
        finally:
            disable_tensor_checker()

    def test_check_numerics(self):
        from paddle_tpu.amp.debugging import check_numerics

        n_nan, n_inf, n_zero = check_numerics(
            paddle.to_tensor([1.0, 0.0, 2.0]), "t", "x")
        assert (int(n_nan), int(n_inf), int(n_zero)) == (0, 0, 1)

    def test_operator_stats(self):
        with collect_operator_stats():
            paddle.matmul(paddle.ones([2, 2]), paddle.ones([2, 2]))
        # stats printed; main contract: no crash and checker uninstalled
        from paddle_tpu.core.amp_state import amp_state

        assert amp_state.checker is None

    def test_operator_stats_preserves_tensor_checker(self):
        from paddle_tpu.core.amp_state import amp_state

        cfg = TensorCheckerConfig(enable=True,
                                  debug_mode=DebugMode.CHECK_NAN_INF)
        enable_tensor_checker(cfg)
        try:
            with collect_operator_stats():
                paddle.sqrt(paddle.to_tensor([-1.0]))  # checker still fires
            assert amp_state.checker == cfg._check  # restored, not cleared
            assert cfg._found  # chained checker saw the nan
        finally:
            disable_tensor_checker()


class TestAmpWithModel:
    def test_training_loop_o1(self):
        from paddle_tpu.optimizer import AdamW

        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        scaler = amp.GradScaler(init_loss_scaling=1024.0)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        losses = []
        for _ in range(5):
            with amp.auto_cast(level="O1"):
                out = model(x)
                loss = (out ** 2).mean()
            scaled = scaler.scale(loss)
            scaled.backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
