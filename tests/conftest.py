"""Test harness config: force an 8-device virtual CPU platform BEFORE jax
import so multi-chip sharding tests run anywhere (driver parity: the judge's
dryrun uses xla_force_host_platform_device_count the same way)."""
import os

# PADDLE_TPU_TESTS_ON_DEVICE=1 runs the suite on the REAL accelerator
# (experiments/tpu_session.sh uses it for on-chip kernel parity — the
# default-on flash specializations must be re-validated on hardware,
# where Mosaic lowering differs from interpret mode)
_ON_DEVICE = os.environ.get("PADDLE_TPU_TESTS_ON_DEVICE",
                            "").lower() not in ("", "0", "false", "no")

if not _ON_DEVICE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
# keep compile cache warm between tests
os.environ.setdefault("JAX_ENABLE_X64", "0")
# numerical-parity tests want f32 accumulation; benchmarks use the hardware
# default (bf16 on MXU)
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

# The environment's sitecustomize may force jax_platforms="axon,cpu" (real
# TPU tunnel) at interpreter start — env vars alone cannot override it, so
# pin CPU via the config API after import.
if not _ON_DEVICE:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# jax 0.4.x: shard_map still lives in jax.experimental; tests import the
# graduated name (`from jax import shard_map`) possibly before paddle_tpu
# — whose __init__ installs a kwarg-translating alias — so make sure the
# alias exists before any test module is collected.
import paddle_tpu  # noqa: E402,F401  (installs the jax.shard_map alias)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
