"""Test harness config: force an 8-device virtual CPU platform BEFORE jax
import so multi-chip sharding tests run anywhere (driver parity: the judge's
dryrun uses xla_force_host_platform_device_count the same way).

Also hosts the tier-1 WALL-TIME BUDGET guard (bottom of this file): a
full `-m 'not slow'` run that exceeds ~800s fails loudly with the
move-to-slow-tier playbook instead of silently drifting into the
driver's 870s kill."""
import os
import sys
import time

# PADDLE_TPU_TESTS_ON_DEVICE=1 runs the suite on the REAL accelerator
# (experiments/tpu_session.sh uses it for on-chip kernel parity — the
# default-on flash specializations must be re-validated on hardware,
# where Mosaic lowering differs from interpret mode)
_ON_DEVICE = os.environ.get("PADDLE_TPU_TESTS_ON_DEVICE",
                            "").lower() not in ("", "0", "false", "no")

if not _ON_DEVICE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
# keep compile cache warm between tests
os.environ.setdefault("JAX_ENABLE_X64", "0")
# numerical-parity tests want f32 accumulation; benchmarks use the hardware
# default (bf16 on MXU)
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

# The environment's sitecustomize may force jax_platforms="axon,cpu" (real
# TPU tunnel) at interpreter start — env vars alone cannot override it, so
# pin CPU via the config API after import.
if not _ON_DEVICE:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# jax 0.4.x: shard_map still lives in jax.experimental; tests import the
# graduated name (`from jax import shard_map`) possibly before paddle_tpu
# — whose __init__ installs a kwarg-translating alias — so make sure the
# alias exists before any test module is collected.
import paddle_tpu  # noqa: E402,F401  (installs the jax.shard_map alias)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


# -- tier-1 wall-time budget guard -------------------------------------------
# The tier-1 suite runs under a hard 870s driver timeout (ROADMAP.md);
# blowing it kills the run at rc=124 with NO per-test attribution, and
# PRs 1 and 6 each burned review cycles rediscovering that the fix is
# moving minutes-scale suites to the slow tier (`pytestmark =
# pytest.mark.slow`, run via `-m slow`). This guard fails the suite
# LOUDLY at ~800s — while everything still passes and the slow culprit
# is attributable via --durations — instead of letting the next PR
# drift into the silent 870s cliff. Scope: only full tier-1-shaped runs
# (a `not slow` markexpr over a substantial collection); tune/disable
# via PADDLE_TPU_TIER1_BUDGET_S (0 = off).
_TIER1_BUDGET_S = float(os.environ.get("PADDLE_TPU_TIER1_BUDGET_S",
                                       "800"))
_TIER1_MIN_TESTS = int(os.environ.get("PADDLE_TPU_TIER1_MIN_TESTS",
                                      "400"))  # skip -k slices / files
_session_t0 = None


def _is_tier1_run(session) -> bool:
    markexpr = getattr(session.config.option, "markexpr", "") or ""
    return ("not slow" in markexpr
            and getattr(session, "testscollected", 0)
            >= _TIER1_MIN_TESTS)


def pytest_sessionstart(session):
    global _session_t0
    _session_t0 = time.monotonic()


def pytest_sessionfinish(session, exitstatus):
    if _session_t0 is None or _TIER1_BUDGET_S <= 0:
        return
    wall = time.monotonic() - _session_t0
    if not _is_tier1_run(session):
        return
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    line = (f"tier-1 wall time: {wall:.0f}s "
            f"(budget {_TIER1_BUDGET_S:.0f}s, driver timeout 870s)")
    if wall <= _TIER1_BUDGET_S:
        if tr is not None:
            tr.write_line(line)
        return
    msg = (
        f"\n{'=' * 72}\n"
        f"TIER-1 WALL-TIME BUDGET EXCEEDED: {line}\n"
        f"The driver kills this suite at 870s (rc=124, no per-test\n"
        f"attribution). Move the slow culprits to the slow tier\n"
        f"(`pytestmark = pytest.mark.slow`, run via `-m slow`) — the\n"
        f"PR 1 / PR 6 precedent — before the next PR hits the cliff.\n"
        f"Find them with: pytest --durations=25 -m 'not slow'.\n"
        f"Tune/disable via PADDLE_TPU_TIER1_BUDGET_S (0 = off).\n"
        f"{'=' * 72}")
    if tr is not None:
        tr.write_line(msg, red=True, bold=True)
    else:
        print(msg, file=sys.stderr)
    if session.exitstatus == 0:
        session.exitstatus = 1
