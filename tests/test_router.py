"""Fleet-level fault tolerance: the health-aware replica router.

ISSUE-10 acceptance on CPU: a :class:`~paddle_tpu.serving.Router` over
N in-process replica Servers routes least-loaded around unhealthy
replicas, FAILS OVER a request whose replica dies or degrades
mid-stream with BITWISE greedy parity (one stable rid, one
uninterrupted stream), opens/half-opens/closes per-replica circuit
breakers, supervises crashed replicas back to life with bounded
exponential backoff, and drains/rolling-restarts one replica at a time
with zero failed requests — plus the ``Server.load()`` snapshot
unification (one lock-light host-side read feeding both the router and
``/healthz``, never blocking behind a wedged scheduler step) and the
router metric/trace surfaces.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, tracing
from paddle_tpu.inference.generation import (
    EngineFault, GenerationConfig, PagedContinuousBatchingEngine)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.serving import (FailoverBudgetExceeded, ReplicaSpec,
                                RequestFailed, RequestRejected, Router,
                                Server, serve_http)
from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

CFG = llama_config("tiny", num_hidden_layers=1)
PROMPT = np.arange(1, 9, dtype=np.int32)


def make_engine(max_batch=2, num_pages=24, page_size=8, max_pages=8,
                **kw):
    # fresh model per engine, SAME seed: replica scheduler threads
    # trace concurrently (a shared model's substituted_state is not),
    # and deterministic init keeps the fleet's weights bitwise equal —
    # the property greedy failover parity rides on
    paddle.seed(0)
    return PagedContinuousBatchingEngine(
        LlamaForCausalLM(CFG), max_batch=max_batch,
        num_pages=num_pages, page_size=page_size, max_pages=max_pages,
        **kw)


def make_spec(engine_factory=make_engine, **server_kw):
    server_kw.setdefault("segment_steps", 2)
    server_kw.setdefault("idle_wait_s", 0.005)
    return ReplicaSpec(engine_factory, server_kwargs=server_kw)


def faulty_fleet(n, server_kw=None, faulty_builds=None):
    """Spec whose FIRST build of each replica slot is FaultyEngine-
    wrapped (supervisor rebuilds come up clean): returns
    (spec, plans) with plans[i] the i-th build's FaultPlan."""
    plans = {}
    builds = {"n": 0}
    faulty = set(range(n) if faulty_builds is None else faulty_builds)

    def factory():
        i = builds["n"]
        builds["n"] += 1
        eng = make_engine()
        if i in faulty:
            plans[i] = FaultPlan()
            return FaultyEngine(eng, plans[i])
        return eng

    kw = dict(server_kw or {})
    kw.setdefault("max_restarts", 0)   # a killed replica DIES instead
    #                                    of recovering in place — the
    #                                    router must absorb it
    return make_spec(factory, **kw), plans


@pytest.fixture(scope="module")
def ref_server():
    """One unfaulted single-replica Server for bitwise references."""
    srv = Server(make_engine(), segment_steps=2, idle_wait_s=0.005)
    yield srv
    srv.shutdown(drain=False)


def ref_tokens(ref_server, prompt, max_new):
    h = ref_server.submit(np.asarray(prompt, np.int32),
                          GenerationConfig(max_new_tokens=max_new))
    return h.result(timeout=120).tolist()


class TestLoadSnapshot:
    """Satellite: Server.load() + /healthz unification — one lock-light
    host-side snapshot, never blocking behind the scheduler."""

    def test_load_keys_and_healthz_consume_same_snapshot(self):
        from urllib.request import urlopen

        srv = Server(make_engine(), segment_steps=2)
        try:
            snap = srv.load()
            for k in ("status", "healthy", "queue_depth",
                      "active_requests", "restarts", "free_slots",
                      "active_slots", "max_batch", "free_pages",
                      "total_pages", "occupancy"):
                assert k in snap, k
            assert snap["status"] == "ok" and snap["healthy"]
            assert snap["free_slots"] == 2 and snap["active_slots"] == 0
            httpd = serve_http(srv, port=0)
            try:
                port = httpd.server_address[1]
                with urlopen(f"http://127.0.0.1:{port}/healthz",
                             timeout=10) as r:
                    body = json.loads(r.read())
                # healthz IS the load() snapshot (fields may move
                # between reads; the SHAPE must match)
                assert set(snap) <= set(body)
                assert body["healthy"] is True
            finally:
                httpd.shutdown()
        finally:
            srv.shutdown(drain=False)

    def test_load_never_blocks_while_scheduler_holds_the_gap(self):
        """Regression: load() must stay readable while the scheduler
        thread is wedged inside a step (that is exactly when a router
        needs it to route AROUND this replica)."""
        plan = FaultPlan().hang_at("decode", nth=1, seconds=8.0)
        srv = Server(FaultyEngine(make_engine(), plan),
                     segment_steps=2, idle_wait_s=0.005)
        try:
            srv.submit(PROMPT, GenerationConfig(max_new_tokens=8))
            deadline = time.monotonic() + 30
            while plan.calls["decode"] < 1:
                assert time.monotonic() < deadline, "hang never engaged"
                time.sleep(0.005)
            t0 = time.monotonic()
            for _ in range(20):
                snap = srv.load()
            dt = time.monotonic() - t0
            assert dt < 0.5, f"20 load() reads took {dt:.3f}s mid-hang"
            assert snap["active_requests"] >= 1
        finally:
            plan.release_hangs()
            srv.shutdown(drain=False)


class TestRouterBasics:
    def test_knob_validation(self):
        with pytest.raises(ValueError, match="replicas"):
            Router(make_spec(), replicas=0, start=False)
        with pytest.raises(ValueError, match="breaker_threshold"):
            Router(make_spec(), replicas=1, breaker_threshold=0,
                   start=False)
        with pytest.raises(ValueError, match="callable"):
            ReplicaSpec("not a factory")
        with pytest.raises(ValueError, match="contradicts"):
            Router([make_spec(), make_spec()], replicas=3, start=False)

    def test_routes_and_matches_single_server(self, ref_server):
        r = Router(make_spec(), replicas=2, monitor_interval_s=0.02)
        try:
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=8))
            toks = h.result(timeout=120).tolist()
            assert toks == ref_tokens(ref_server, PROMPT, 8)
            assert h.failovers == 0
            snap = r.load()
            assert snap["status"] == "ok" and snap["healthy"]
            assert [e["status"] for e in snap["replicas"]] == ["ok",
                                                              "ok"]
            assert all(e["breaker"]["state"] == "closed"
                       for e in snap["replicas"])
        finally:
            r.shutdown(drain=False)

    def test_drained_replica_excluded_from_routing(self):
        r = Router(make_spec(), replicas=2, monitor_interval_s=0.02)
        try:
            assert r.drain(0, timeout=30)   # no in-flight work: instant
            for _ in range(2):
                h = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
                h.result(timeout=120)
                assert h.replica == 1   # replica 0 is out of rotation
            snap = r.load()
            assert snap["replicas"][0]["status"] == "draining"
            assert snap["status"] == "degraded"   # partial fleet...
            assert snap["healthy"]                # ...still serves
        finally:
            r.shutdown(drain=False)

    def test_prompt_that_can_never_fit_rejected_at_submit(self):
        r = Router(make_spec(), replicas=1, monitor_interval_s=0.02)
        try:
            with pytest.raises(ValueError, match="max_len"):
                r.submit(np.arange(1, 30, dtype=np.int32),
                         GenerationConfig(max_new_tokens=4096))
        finally:
            r.shutdown(drain=False)

    def test_heterogeneous_fleet_routes_to_the_replica_that_fits(
            self, ref_server):
        """A list of DIFFERING specs: a per-replica capacity verdict
        (ValueError from the small replica) must route the request to
        the larger one, not fail it fleet-wide; a request fitting NO
        spec still fails terminally."""
        small = make_spec(lambda: make_engine(max_pages=4))  # max_len 32
        big = make_spec()                                    # max_len 64
        r = Router([small, big], monitor_interval_s=0.02)
        try:
            # 8 + 40 = 48: over the small replica's 32, inside 64 —
            # idle-tie routing tries small first, gets the capacity
            # verdict, and lands on big
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=40))
            toks = h.result(timeout=120).tolist()
            assert h.replica == 1
            assert toks == ref_tokens(ref_server, PROMPT, 40)
            # fitting NO spec is still caught at submit (the precheck
            # uses the fleet's LARGEST max_len)
            with pytest.raises(ValueError, match="max_len"):
                r.submit(np.arange(1, 30, dtype=np.int32),
                         GenerationConfig(max_new_tokens=40))
        finally:
            r.shutdown(drain=False)


class TestFailover:
    def test_replica_killed_mid_stream_bitwise_parity(self,
                                                      ref_server):
        """THE failover contract: the serving replica dies mid-stream,
        the request migrates with its emitted prefix, the client sees
        ONE uninterrupted stream whose tokens are bitwise what an
        unfaulted run produces, and the router timeline records
        route -> failover -> route under the stable router rid."""
        spec, plans = faulty_fleet(2)
        tracing.clear()
        tracing.enable()
        r = Router(spec, replicas=2, monitor_interval_s=0.02,
                   replica_backoff_s=0.05, degraded_poll_s=0.1)
        try:
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=24))
            stream = h.stream(timeout=120)
            toks = [next(stream)]       # first token pins the replica
            first_rep = h.replica
            plans[first_rep].kill("decode")
            toks.extend(stream)         # the SAME iterator keeps going
            assert h.status == "finished"
            assert h.failovers >= 1 and h.replica != first_rep
            assert toks == ref_tokens(ref_server, PROMPT, 24)
            phases = [e["phase"] for e in h.timeline()]
            assert "route" in phases and "failover" in phases
            assert phases.index("route") < phases.index("failover") \
                < len(phases) - 1 - phases[::-1].index("route")
            # the finish rides the same router-scoped timeline
            assert phases[-1] == "finish" or "finish" in phases
        finally:
            r.shutdown(drain=False)
            tracing.disable()
            tracing.clear()

    def test_failover_budget_typed_failure(self):
        """Every replica the request lands on dies under it: past
        max_failovers the request fails with FailoverBudgetExceeded as
        its typed cause instead of migrating forever."""
        builds = {"n": 0}
        plans = {}

        def factory():
            i = builds["n"]
            builds["n"] += 1
            plans[i] = FaultPlan().kill("decode", nth=1)
            return FaultyEngine(make_engine(), plans[i])

        spec = make_spec(factory, max_restarts=0)
        r = Router(spec, replicas=2, max_failovers=0,
                   monitor_interval_s=0.02, replica_backoff_s=0.05,
                   degraded_poll_s=0.1)
        try:
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=8))
            with pytest.raises(RequestFailed) as ei:
                h.result(timeout=120)
            assert isinstance(ei.value.__cause__,
                              FailoverBudgetExceeded)
        finally:
            r.shutdown(drain=False)


class TestBreaker:
    def test_opens_after_k_failures_then_half_open_recovers(self):
        """Replica 0 faults; its Server recovers IN PLACE (PR 4
        supervised recovery) but the router has already moved on: the
        breaker OPENs at the threshold, routing avoids replica 0 while
        open (no hammering a sick replica), and after the backoff the
        next request is the HALF-OPEN probe that closes it."""
        builds = {"n": 0}
        plan = FaultPlan()   # armed mid-test, once both replicas are
        #                      warm — the warm-up traffic must not
        #                      trip it

        def factory():
            i = builds["n"]
            builds["n"] += 1
            if i == 0:
                return FaultyEngine(make_engine(), plan)
            return make_engine()

        spec = make_spec(factory, max_restarts=3,
                         restart_backoff_s=0.2)
        r = Router(spec, replicas=2, breaker_threshold=1,
                   breaker_backoff_s=2.0, monitor_interval_s=0.02,
                   degraded_poll_s=0.05)
        try:
            # warm BOTH replicas (compile off the measured path, so
            # the post-failover requests run fast inside the breaker's
            # open window): first request pins idle-tie replica 0;
            # submitting the second while it is mid-flight routes
            # least-loaded to replica 1
            wa = r.submit(PROMPT, GenerationConfig(max_new_tokens=16))
            next(wa.stream(timeout=120))
            assert wa.replica == 0
            wb = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
            wb.result(timeout=120)
            assert wb.replica == 1
            wa.result(timeout=120)
            # ONE single-shot engine fault on replica 0's next decode:
            # it degrades (in-place recovery backoff) then returns to
            # health WITHOUT a supervisor rebuild — the breaker, not
            # the supervisor, governs its re-entry
            plan.raise_at("decode", nth=plan.calls["decode"] + 1,
                          exc=lambda: EngineFault("injected"))
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=8))
            h.result(timeout=120)           # failed over to replica 1
            assert h.failovers >= 1 and h.replica == 1
            b0 = r.load()["replicas"][0]["breaker"]
            assert b0["opens"] == 1
            assert b0["state"] in ("open", "half_open")
            # while OPEN, new work avoids replica 0 even once its own
            # recovery finished (both replicas warm: this completes
            # well inside the 2s window)
            h2 = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
            h2.result(timeout=120)
            assert h2.replica == 1
            # wait out the open window AND replica 0's own recovery,
            # then the next request is the half-open probe
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                s0 = r.load()["replicas"][0]
                if (s0["status"] == "ok"
                        and s0["breaker"]["state"] != "open"):
                    break
                time.sleep(0.05)
            h3 = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
            h3.result(timeout=120)
            assert h3.replica == 0          # the probe
            assert h3.failovers == 0
            b0 = r.load()["replicas"][0]["breaker"]
            assert b0["state"] == "closed" and b0["failures"] == 0
            assert r.load()["breaker_opens"] == 1
        finally:
            r.shutdown(drain=False)


class TestProbeRelease:
    def test_cancelled_half_open_probe_frees_the_probe_slot(self):
        """Regression: a half-open probe request that ends CANCELLED
        (neither replica-success nor replica-failure) must release the
        probe slot — before the fix rep.probing stayed True forever
        and the recovered replica was never routed to again."""
        r = Router(make_spec(), replicas=1, breaker_threshold=1,
                   breaker_backoff_s=0.05, monitor_interval_s=0.02,
                   degraded_poll_s=0.05)
        try:
            rep = r._replicas[0]
            # force the breaker state machine by hand (driving a real
            # engine fault here would add seconds for no extra truth):
            # open, elapsed -> the next pick is the half-open probe
            with r._lock:
                rep.breaker = 2          # BREAKER_OPEN
                rep.open_until = 0.0     # already elapsed
                rep.opens = 1
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=32))
            deadline = time.monotonic() + 10
            while h.replica is None and time.monotonic() < deadline:
                time.sleep(0.005)
            h.cancel()                   # the probe dies a user-cancel
            deadline = time.monotonic() + 30
            while not h.done and time.monotonic() < deadline:
                time.sleep(0.005)
            assert h.status == "cancelled"
            # the probe slot is free again: the next request routes
            # (it becomes the new probe) and closes the breaker
            h2 = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
            h2.result(timeout=120)
            assert h2.replica == 0
            assert r.load()["replicas"][0]["breaker"]["state"] \
                == "closed"
        finally:
            r.shutdown(drain=False)


class TestDrainAndRollingRestart:
    def test_fleet_drain_rejects_new_work_but_finishes_inflight(self):
        """Satellite: drain rejects new work with 503 (HTTP) /
        RequestRejected(draining) while in-flight handles run to
        completion."""
        import http.client

        r = Router(make_spec(), replicas=2, monitor_interval_s=0.02)
        httpd = serve_http(r, port=0)
        try:
            port = httpd.server_address[1]
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=32))
            drained = {}

            def _drain():
                drained["ok"] = r.drain(timeout=120)

            t = threading.Thread(target=_drain, daemon=True)
            t.start()
            deadline = time.monotonic() + 10
            while not r.load()["status"] == "draining":
                assert time.monotonic() < deadline
                time.sleep(0.005)
            with pytest.raises(RequestRejected) as ei:
                r.submit(PROMPT, GenerationConfig(max_new_tokens=2))
            assert ei.value.reason == "draining"
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("POST", "/generate", json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 2}),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 503
            assert json.loads(resp.read())["reason"] == "draining"
            conn.close()
            t.join(timeout=120)
            assert drained.get("ok") is True
            assert h.status == "finished"
            assert len(h.tokens_so_far()) == 32
        finally:
            httpd.shutdown()
            r.shutdown(drain=False)

    def test_rolling_restart_zero_failed_requests(self):
        """Restart the whole fleet one replica at a time while
        requests keep arriving: every handle finishes, none fail —
        the fleet-level analogue of reset_state()."""
        r = Router(make_spec(), replicas=2, monitor_interval_s=0.02)
        try:
            handles = [r.submit(PROMPT,
                                GenerationConfig(max_new_tokens=16))
                       for _ in range(2)]
            done = threading.Event()

            def _traffic():
                while not done.is_set():
                    try:
                        handles.append(r.submit(
                            PROMPT, GenerationConfig(max_new_tokens=4)))
                    except RequestRejected:
                        pass    # a 1-replica window may be busy;
                        #         rejection is backpressure, not failure
                    time.sleep(0.05)

            t = threading.Thread(target=_traffic, daemon=True)
            t.start()
            try:
                assert r.rolling_restart(timeout=120)
            finally:
                done.set()
                t.join(timeout=10)
            for h in handles:
                h.result(timeout=120)      # raises on any non-finish
                assert h.status == "finished"
            snap = r.load()
            assert [e["status"] for e in snap["replicas"]] == ["ok",
                                                              "ok"]
            # deliberate restarts are counted — but NOT against the
            # supervision budget (max_replica_restarts stays whole)
            assert all(e["deliberate_restarts"] >= 1
                       for e in snap["replicas"])
            assert all(e["restarts"] == 0 for e in snap["replicas"])
        finally:
            r.shutdown(drain=False)


class TestSupervisor:
    def test_restarts_dead_replica_within_backoff_bound(self):
        """A killed replica is detected, named in fleet /healthz with
        its breaker state, and rebuilt within monitor_interval +
        backoff + build time."""
        from urllib.request import urlopen

        spec, plans = faulty_fleet(2, faulty_builds=[0])
        # 2s restart backoff: the down window must be wide enough for
        # healthz to observe the casualty before resurrection
        r = Router(spec, replicas=2, monitor_interval_s=0.02,
                   replica_backoff_s=2.0, breaker_threshold=1,
                   degraded_poll_s=0.1)
        httpd = serve_http(r, port=0)
        try:
            port = httpd.server_address[1]
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=24))
            it = h.stream(timeout=120)
            next(it)
            assert h.replica == 0      # idle tie routes to replica 0
            t_kill = time.monotonic()
            plans[0].kill("decode")
            # wait for the supervisor to DETECT the death (the victim
            # was mid-decode: its scheduler dies within one segment)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                s0 = r.load()["replicas"][0]
                if s0["status"] != "ok":
                    break
                time.sleep(0.01)
            # fleet healthz NAMES the casualty while it is down
            with urlopen(f"http://127.0.0.1:{port}/healthz",
                         timeout=10) as resp:
                body = json.loads(resp.read())
            assert resp.status == 200  # one dead replica degrades,
            #                            never fails, the fleet
            rep0 = body["replicas"][0]
            assert rep0["status"] in ("failed", "restarting",
                                      "warming")
            assert rep0["breaker"]["state"] in ("open", "half_open",
                                                "closed")
            assert body["status"] in ("degraded", "ok")
            list(it)                   # failover to 1, completes
            assert h.status == "finished" and h.replica == 1
            # ... and the supervisor brings it back within its bound
            # (poll interval + 2s backoff + engine build; generous
            # CI slack)
            deadline = t_kill + 60
            while time.monotonic() < deadline:
                s0 = r.load()["replicas"][0]
                if s0["status"] == "ok":
                    break
                time.sleep(0.05)
            assert s0["status"] == "ok", s0
            assert s0["restarts"] == 1
            # the rebuilt replica actually serves
            assert r.drain(1, timeout=30)   # push traffic to 0
            h2 = r.submit(PROMPT, GenerationConfig(max_new_tokens=4))
            h2.result(timeout=120)
            assert h2.replica == 0
        finally:
            httpd.shutdown()
            r.shutdown(drain=False)


class TestMetrics:
    def test_router_series_created_and_retired(self):
        monitor.enable()
        monitor.reset()
        try:
            spec, plans = faulty_fleet(2, faulty_builds=[0])
            r = Router(spec, replicas=2, breaker_threshold=1,
                       monitor_interval_s=0.02, replica_backoff_s=0.1,
                       degraded_poll_s=0.1)
            label = r.monitor_router
            h = r.submit(PROMPT, GenerationConfig(max_new_tokens=16))
            next(h.stream(timeout=120))
            plans[h.replica].kill("decode")
            h.result(timeout=120)
            assert h.failovers >= 1

            def router_series():
                out = []
                for name, meta in monitor.snapshot()["metrics"].items():
                    for s in meta["samples"]:
                        if s["labels"].get("router") == label:
                            out.append((name, s["labels"],
                                        s["value"]))
                return out

            series = router_series()
            names = {n for n, _, _ in series}
            assert "paddle_tpu_router_requests_total" in names
            assert "paddle_tpu_router_failovers_total" in names
            assert "paddle_tpu_router_breaker_state" in names
            fo = [v for n, lb, v in series
                  if n == "paddle_tpu_router_requests_total"
                  and lb["outcome"] == "failover"]
            assert sum(fo) >= 1
            r.shutdown(drain=False)
            assert router_series() == [], (
                "router series survived shutdown")
        finally:
            monitor.reset()
            monitor.disable()


class TestChaosAcceptance:
    """ISSUE-10 acceptance: 3 in-process replicas under seeded load,
    one replica killed mid-flight — 100% of requests complete with
    bitwise greedy parity vs unfaulted runs, fleet healthz names the
    dead replica + breaker state, the supervisor restarts it within
    its backoff bound, and a rolling restart over the live fleet
    finishes with zero failed handles."""

    def test_three_replicas_one_killed_all_complete_bitwise(
            self, ref_server):
        rng = np.random.RandomState(7)
        prompts = [rng.randint(1, 200, (int(n),)).astype(np.int32)
                   for n in rng.randint(4, 12, size=6)]
        budgets = [int(b) for b in rng.randint(8, 20, size=6)]
        refs = [ref_tokens(ref_server, p, b)
                for p, b in zip(prompts, budgets)]

        spec, plans = faulty_fleet(3)
        r = Router(spec, replicas=3, monitor_interval_s=0.02,
                   replica_backoff_s=0.25, breaker_threshold=2,
                   degraded_poll_s=0.1, max_failovers=3)
        try:
            handles = []
            for p, b in zip(prompts, budgets):
                handles.append(r.submit(
                    p, GenerationConfig(max_new_tokens=b)))
                time.sleep(0.02)       # seeded stagger
            # kill whichever replica serves the FIRST request, once it
            # is demonstrably mid-flight
            it = handles[0].stream(timeout=120)
            next(it)
            victim = handles[0].replica
            plans[victim].kill("decode")
            outs = [h.result(timeout=180).tolist() for h in handles]
            assert outs == refs, "failover broke greedy parity"
            assert sum(h.failovers for h in handles) >= 1
            # the supervisor resurrects the victim within its bound
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sv = r.load()["replicas"][victim]
                if sv["status"] == "ok":
                    break
                time.sleep(0.05)
            assert sv["status"] == "ok" and sv["restarts"] == 1
            # rolling restart over the LIVE fleet: zero failed handles
            more = [r.submit(p, GenerationConfig(max_new_tokens=4))
                    for p in prompts[:3]]
            assert r.rolling_restart(timeout=120)
            for h in more:
                h.result(timeout=120)
                assert h.status == "finished"
            assert r.load()["status"] == "ok"
        finally:
            r.shutdown(drain=False)
