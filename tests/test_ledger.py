"""Program-ledger observability suite (ISSUE 16).

Covers ``paddle_tpu.monitor.ledger`` end to end on CPU:

- program identity: ``program_id`` is stable across calls/processes
  (pure function of name + treedef + avals + sharding), and distinct
  shapes/dtypes/static args get distinct ids;
- the LEDGER itself: first compile captures XLA cost analysis (flops,
  bytes accessed, output bytes) plus compile seconds; steady-state
  dispatches feed the merge-exact latency digest (compile dispatches
  are counted but excluded from the digest); the per-program monitor
  series track ``rec.dispatches`` exactly;
- ownership: ``release(owner)`` drops only that owner's programs and
  retires their series; co-owned and ownerless programs survive;
- ``profile()`` / ``merge_profiles()``: derived roofline fields
  (achieved FLOP/s, arithmetic intensity, MFU, bound verdict) against
  the calibrated per-backend peak table, and the cross-replica merge
  is exact (counts add, digests merge bucket-for-bucket);
- the per-backend peak table (``paddle_tpu.device.peaks``) and the
  provenance ``env_stamp`` header;
- ``tools/bench_diff.py``: direction-aware metric classification and
  record loading across the formats it supports;
- SERVER integration: ``GET /profile`` over HTTP, Server.load()'s
  profile block, and THE acceptance scenario — a warmed mixed-feature
  run (chunked prefill + prefix hit + speculative decoding + int8 KV
  + LoRA) in which every compiled serving program appears in the
  ledger with nonzero cost analysis and a dispatch count matching the
  monitored_jit counters.
"""
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.device import peaks as peaks_mod
from paddle_tpu.inference.generation import (GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.monitor import ledger
from paddle_tpu.monitor.provenance import env_stamp
from paddle_tpu.serving import Server, serve_http

_MODEL = None


def tiny_model():
    """ONE tiny llama shared by the whole module (jit programs are
    keyed on shapes — reusing it keeps the suite to a handful of
    compiles)."""
    global _MODEL
    if _MODEL is None:
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        cfg = llama_config("tiny", num_hidden_layers=1)
        _MODEL = (LlamaForCausalLM(cfg), cfg)
    return _MODEL


def make_adapter(model, seed, targets=("q", "v"), rank=2, scale=0.6):
    _, shapes = model.lora_shapes(targets)
    rng = np.random.default_rng(seed)
    return {t: (rng.standard_normal((rank, d_in)).astype(np.float32)
                * scale,
                rng.standard_normal((d_out, rank)).astype(np.float32)
                * scale)
            for t, (d_in, d_out) in shapes.items()}


def paged_engine(model, max_batch=4, num_pages=64, page_size=4,
                 max_pages=16, **kw):
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


@pytest.fixture()
def led():
    """Monitor + ledger armed for one test, both swept clean after."""
    monitor.enable()
    monitor.reset()
    ledger.reset()
    ledger.enable()
    yield ledger
    ledger.disable()
    ledger.reset()
    monitor.reset()
    monitor.disable()


def _series(name):
    """{program-label: value} for one of the ledger's metric names."""
    out = {}
    m = monitor.snapshot()["metrics"].get(name)
    for s in (m or {}).get("samples", []):
        key = s["labels"].get("program", "?")
        out[key] = s.get("value", s.get("count"))
    return out


def _mm(owner=None, label="lg_mm"):
    return monitor.monitored_jit(
        lambda a, b: a @ b, name=label, owner=owner)


# ---------------------------------------------------------------- id


class TestProgramId:
    def test_stable_and_distinct(self):
        a = np.zeros((4, 8), np.float32)
        b = np.zeros((8, 4), np.float32)
        pid1 = ledger.program_id("f", (a, b), {})
        pid2 = ledger.program_id("f", (a + 1, b), {})   # values ignored
        assert pid1 == pid2
        assert pid1.startswith("f:")
        # different shape, dtype, name, or static arg → different id
        assert ledger.program_id("f", (a.T, b), {}) != pid1
        assert ledger.program_id(
            "f", (a.astype(np.int32), b), {}) != pid1
        assert ledger.program_id("g", (a, b), {}) != pid1
        assert ledger.program_id("f", (a, b, 3), {}) != pid1
        assert ledger.program_id("f", (a, b), {"k": 1}) != pid1

    def test_monitored_jit_exposes_variants(self, led):
        f = _mm()
        x = np.eye(8, dtype=np.float32)
        f(x, x)
        f(np.ones((4, 8), np.float32), np.ones((8, 4), np.float32))
        pids = set(f._program_ids.values())
        assert len(pids) == 2
        assert pids == set(ledger.profile()["programs"])


# ------------------------------------------------------------ ledger


class TestLedgerRecord:
    def test_compile_then_dispatch(self, led):
        f = _mm(owner="lg_e0")
        x = np.full((16, 16), 0.5, np.float32)
        f(x, x)                          # compile dispatch
        for _ in range(3):
            f(x, x)                      # steady state
        prof = ledger.profile()
        (pid,) = list(prof["programs"])
        rec = prof["programs"][pid]
        assert rec["name"] == "lg_mm"
        assert rec["compiles"] == 1
        assert rec["dispatches"] == 4
        assert rec["compile_seconds"] > 0
        # cost analysis captured once, nonzero on CPU
        assert rec["flops"] and rec["flops"] > 0
        assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
        # the digest only sees the 3 steady-state dispatches — the
        # compile wall-clock is charged to compile_seconds instead
        assert rec["summary"]["count"] == 3
        assert rec["total_seconds"] < rec["compile_seconds"]
        # derived roofline fields present and sane
        assert rec["intensity"] > 0
        assert rec["achieved_flops_per_s"] > 0
        assert 0 <= rec["mfu"] <= 1.0
        assert rec["bound"] in ("memory-bound", "compute-bound")

    def test_series_match_dispatches(self, led):
        f = _mm(owner="lg_e0")
        x = np.ones((8, 8), np.float32)
        for _ in range(5):
            f(x, x)
        (pid,) = list(ledger.profile()["programs"])
        assert _series(ledger.DISPATCH_COUNTER)[pid] == 5
        assert _series(ledger.SECONDS_COUNTER)[pid] >= 0
        assert pid in _series(ledger.MFU_GAUGE)
        # and the per-program jit-miss counters split by program id
        miss = {}
        m = monitor.snapshot()["metrics"].get(
            "paddle_tpu_jit_cache_miss_total")
        for s in (m or {}).get("samples", []):
            miss[s["labels"]["program"]] = s["value"]
        assert miss.get(pid) == 1

    def test_disabled_is_invisible(self):
        ledger.disable()
        ledger.reset()
        f = _mm()
        x = np.ones((4, 4), np.float32)
        f(x, x)
        assert ledger.profile()["programs"] == {}


class TestOwnership:
    def test_release_scoped(self, led):
        fa = _mm(owner="lg_a", label="lg_fa")
        fb = _mm(owner="lg_b", label="lg_fb")
        fn = _mm(owner=None, label="lg_fn")
        x = np.ones((8, 8), np.float32)
        fa(x, x); fb(x, x); fn(x, x)
        assert len(ledger.profile()["programs"]) == 3
        assert len(ledger.owned_programs("lg_a")) == 1
        dropped = ledger.release("lg_a")
        assert dropped == 1
        progs = ledger.profile()["programs"]
        names = {r["name"] for r in progs.values()}
        assert names == {"lg_fb", "lg_fn"}          # ownerless survives
        assert ledger.owned_programs("lg_a") == []
        # released program's series are retired too
        live = set(_series(ledger.DISPATCH_COUNTER))
        assert live == set(progs)

    def test_coowned_survives_single_release(self, led):
        f = _mm(owner="lg_a", label="lg_sh")
        x = np.ones((4, 4), np.float32)
        f(x, x)
        (pid,) = list(ledger.profile()["programs"])
        # second owner touches the same program id
        ledger.record(pid, "lg_sh", "lg_b", f._jitted, (x, x), {},
                      1e-4, False)
        assert ledger.release("lg_a") == 0           # still co-owned
        assert pid in ledger.profile()["programs"]
        assert ledger.release("lg_b") == 1
        assert ledger.profile()["programs"] == {}


class TestProfileMerge:
    def test_owner_filter_and_top_k(self, led):
        fa = _mm(owner="lg_a", label="lg_fa")
        fb = _mm(owner="lg_b", label="lg_fb")
        x = np.ones((8, 8), np.float32)
        fa(x, x); fb(x, x)
        only_a = ledger.profile(owners=["lg_a"])
        assert {r["name"] for r in only_a["programs"].values()} \
            == {"lg_fa"}
        prof = ledger.profile(top_k=1)
        assert len(prof["top"]) == 1
        # top_k truncates the ranking only — programs stay complete so
        # cross-replica merges never lose rows
        assert len(prof["programs"]) == 2
        assert prof["peaks"]["peak_flops"] > 0

    def test_merge_is_exact(self, led):
        f = _mm(label="lg_m")
        x = np.ones((8, 8), np.float32)
        f(x, x); f(x, x); f(x, x)
        shard = ledger.profile()
        merged = ledger.merge_profiles([shard, shard, None, {}])
        (pid,) = list(merged["programs"])
        rec, one = merged["programs"][pid], shard["programs"][pid]
        assert rec["dispatches"] == 2 * one["dispatches"]
        assert rec["compiles"] == 2 * one["compiles"]
        assert rec["summary"]["count"] == 2 * one["summary"]["count"]
        assert rec["summary"]["max"] == one["summary"]["max"]
        assert rec["flops"] == one["flops"]
        assert merged["peaks"] == shard["peaks"]


# ---------------------------------------------- peaks + provenance


class TestPeaksAndProvenance:
    def test_cpu_calibration_record(self):
        pk = peaks_mod.peaks()
        for key in ("device_kind", "platform", "peak_flops",
                    "peak_bytes_per_s", "machine_balance", "source"):
            assert key in pk
        assert pk["peak_flops"] > 0
        assert pk["peak_bytes_per_s"] > 0
        assert pk["machine_balance"] == pytest.approx(
            pk["peak_flops"] / pk["peak_bytes_per_s"])

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "123e12")
        pk = peaks_mod.peaks(refresh=True)
        assert pk["peak_flops"] == pytest.approx(123e12)
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS")
        assert peaks_mod.peaks(refresh=True)["peak_flops"] != \
            pytest.approx(123e12)

    def test_env_stamp(self):
        st = env_stamp()
        for key in ("jax", "python", "backend", "device_kind",
                    "device_count", "hostname", "pid"):
            assert key in st
        # extras merge into a copy, never the cached stamp
        st2 = env_stamp(extra={"arm": "on"})
        assert st2["arm"] == "on"
        assert "arm" not in env_stamp()


# -------------------------------------------------------- bench_diff


class TestBenchDiff:
    def test_classification_directions(self):
        from tools.bench_diff import classify
        assert classify("serve_tpot_p50_ms", "ms") == "lower"
        assert classify("serve_throughput", "tok/s") == "higher"
        assert classify("bench_mfu", "") == "higher"
        assert classify("compile_seconds", "s") == "lower"

    def test_regression_and_clean_exit(self, tmp_path, capsys):
        from tools.bench_diff import main
        old = tmp_path / "old.jsonl"
        new = tmp_path / "new.jsonl"
        base = [{"metric": "serve_tpot_p50_ms", "value": 10.0,
                 "unit": "ms"},
                {"metric": "serve_throughput", "value": 100.0,
                 "unit": "tok/s"}]
        old.write_text("\n".join(json.dumps(r) for r in base))
        new.write_text("\n".join(json.dumps(r) for r in base))
        assert main([str(old), str(new)]) == 0
        worse = [dict(base[0], value=12.0), base[1]]
        new.write_text("\n".join(json.dumps(r) for r in worse))
        assert main([str(old), str(new)]) == 1
        out = capsys.readouterr().out
        assert "serve_tpot_p50_ms" in out
        # higher-better direction: a throughput DROP regresses too
        slower = [base[0], dict(base[1], value=70.0)]
        new.write_text("\n".join(json.dumps(r) for r in slower))
        assert main([str(old), str(new)]) == 1

    def test_wrapper_and_baseline_formats(self, tmp_path):
        from tools.bench_diff import load_records, main
        tail = "\n".join([
            "noise line",
            json.dumps({"metric": "m1", "value": 1.0, "unit": "s"}),
            json.dumps({"metric": "bench_env", "backend": "cpu"}),
        ])
        wrap = tmp_path / "BENCH_r01.json"
        wrap.write_text(json.dumps(
            {"n": 1, "cmd": "x", "rc": 0, "tail": tail}))
        recs, env = load_records(str(wrap))
        assert [r["metric"] for r in recs] == ["m1"]
        assert env and env.get("backend") == "cpu"
        # --write-baseline → --baseline round trip
        basefile = tmp_path / "baseline.json"
        assert main([str(wrap), "--write-baseline",
                     str(basefile)]) == 0
        assert main([str(wrap), "--baseline", str(basefile)]) == 0
        recs2, _ = load_records(str(basefile))
        assert [r["metric"] for r in recs2] == ["m1"]


# --------------------------------------------------- server surface


def _drain(handles):
    return [h.result(timeout=120) for h in handles]


class TestServerProfile:
    def test_acceptance_mixed_feature_run(self, led):
        """THE acceptance scenario: a warmed mixed-feature run —
        chunked prefill + prefix hit + speculative decoding + int8 KV
        + LoRA — leaves every compiled serving program in the ledger
        with nonzero cost analysis and a dispatch count matching the
        monitored_jit counters."""
        model, cfg = tiny_model()
        eng = paged_engine(model, prefill_chunk=8, prefix_cache=True,
                           kv_dtype="int8", draft_k=4,
                           lora_capacity=2, lora_rank=2,
                           lora_targets=("q", "v"))
        eng.load_adapter("la", make_adapter(model, 7))
        srv = Server(eng, segment_steps=2)
        rng = np.random.RandomState(0)
        shared = rng.randint(0, cfg.vocab_size, (12,)).astype(np.int32)

        def gen(**kw):
            return GenerationConfig(max_new_tokens=6,
                                    eos_token_id=None, **kw)

        try:
            hs = [
                # long prompt → chunked prefill; second one hits the
                # shared-prefix cache
                srv.submit(np.concatenate([shared, shared[:4]]), gen()),
                srv.submit(np.concatenate([shared, shared[2:6]]),
                           gen()),
                srv.submit(shared[:6],
                           gen(speculative=True, draft_k=4)),
                srv.submit(shared[:8], gen(adapter="la")),
            ]
            _drain(hs)
            # warmed: replay the same mix so steady-state dispatches
            # exist beyond the compile calls
            hs = [srv.submit(np.concatenate([shared, shared[:4]]),
                             gen()),
                  srv.submit(shared[:6],
                             gen(speculative=True, draft_k=4)),
                  srv.submit(shared[:8], gen(adapter="la"))]
            _drain(hs)

            prof = srv.profile()
            progs = prof["programs"]
            assert progs, "mixed-feature run registered no programs"
            # the feature mix actually exercised distinct programs
            names = {r["name"] for r in progs.values()}
            assert any("prefill" in n for n in names)
            assert any("spec" in n for n in names)
            counter = _series(ledger.DISPATCH_COUNTER)
            for pid, rec in progs.items():
                assert rec["flops"] and rec["flops"] > 0, \
                    f"{rec['name']}: no cost analysis"
                assert rec["bytes_accessed"] and \
                    rec["bytes_accessed"] > 0
                assert rec["compiles"] >= 1
                assert counter[pid] == rec["dispatches"], \
                    f"{rec['name']}: counter != ledger"
            # Server.load() carries the compact profile block
            load = srv.load()
            assert load["profile"]["programs"] == len(progs)
            assert load["profile"]["top"]
        finally:
            srv.shutdown()
            eng.close()
        # engine retirement swept the ledger and its series
        assert ledger.profile()["programs"] == {}
        assert _series(ledger.DISPATCH_COUNTER) == {}

    def test_http_get_profile(self, led):
        model, cfg = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=2)
        httpd = serve_http(srv, port=0)
        try:
            h = srv.submit(np.arange(6, dtype=np.int32) % cfg.vocab_size,
                           GenerationConfig(max_new_tokens=4,
                                            eos_token_id=None))
            h.result(timeout=120)
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/profile") as r:
                doc = json.loads(r.read())
            assert doc["programs"]
            assert doc["peaks"]["peak_flops"] > 0
        finally:
            httpd.shutdown()
            srv.shutdown()
            eng.close()
