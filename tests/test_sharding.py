"""ZeRO sharding tests (reference contract: sharding-vs-DP parity,
test/collective/fleet/hybrid_parallel_sharding_model.py; plus placement
checks that states/params are actually scattered over the sharding axis)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.optimizer import AdamW


def make_model():
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))


def train_steps(model, opt, x, n=3):
    losses = []
    for _ in range(n):
        loss = (model(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


class TestGroupSharded:
    def setup_method(self, _):
        set_mesh(build_mesh(sharding=8))

    def test_bad_level(self):
        m = make_model()
        opt = AdamW(parameters=m.parameters())
        with pytest.raises(ValueError):
            group_sharded_parallel(m, opt, level="zz")

    def test_os_states_sharded(self):
        m = make_model()
        opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
        m, opt, _ = group_sharded_parallel(m, opt, level="os")
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        train_steps(m, opt, x, 1)
        # moment accumulators for the [16,32] weight must be sharded
        from paddle_tpu.core.tensor import Tensor

        sharded = False
        for accs in opt._accumulators.values():
            for v in accs.values():
                val = v._value if isinstance(v, Tensor) else v
                spec = getattr(val, "sharding", None)
                if spec is not None and "sharding" in str(
                        getattr(spec, "spec", "")):
                    sharded = True
        assert sharded

    def test_os_g_wrappers(self):
        from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
            GroupShardedOptimizerStage2, GroupShardedStage2)

        m = make_model()
        opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
        m2, opt2, _ = group_sharded_parallel(m, opt, level="os_g")
        assert isinstance(opt2, GroupShardedOptimizerStage2)
        assert isinstance(m2, GroupShardedStage2)
        specs = m2.grad_specs()
        assert any("sharding" in str(s) for s in specs.values())
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        losses = train_steps(m2, opt2, x)
        assert losses[-1] < losses[0]

    def test_os_g_grads_placed_sharded(self):
        """grad_pspec must be CONSUMED: eager .grad lands sharded."""
        m = make_model()
        opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
        m2, opt2, _ = group_sharded_parallel(m, opt, level="os_g")
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        loss = (m2(x) ** 2).mean()
        loss.backward()
        w = m2._layers[0].weight
        assert w.grad is not None
        assert "sharding" in str(w.grad._value.sharding.spec)
        opt2.clear_grad()

    def test_p_g_os_params_scattered(self):
        m = make_model()
        opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
        m3, opt3, _ = group_sharded_parallel(m, opt, level="p_g_os")
        w = m3._layers[0].weight
        sh = w._value.sharding
        assert "sharding" in str(getattr(sh, "spec", ""))
        # logical value is still the full array
        assert tuple(w.shape) == (16, 32)
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        losses = train_steps(m3, opt3, x)
        assert losses[-1] < losses[0]
        # gather API returns host copies
        full = m3.get_all_parameters(convert2cpu=True)
        assert full[0].shape == (16, 32)

    def test_sharding_parity_vs_plain(self):
        """The ZeRO memory layout must not change the math (reference
        hybrid_parallel_sharding_model.py contract)."""
        x = np.random.randn(4, 16).astype(np.float32)

        m_ref = make_model()
        m = make_model()
        m.set_state_dict(m_ref.state_dict())  # sync BEFORE training

        opt_ref = AdamW(learning_rate=1e-2, parameters=m_ref.parameters())
        ref_losses = train_steps(m_ref, opt_ref, paddle.to_tensor(x))

        opt = AdamW(learning_rate=1e-2, parameters=m.parameters())
        m, opt, _ = group_sharded_parallel(m, opt, level="p_g_os")
        zero_losses = train_steps(m, opt, paddle.to_tensor(x))
        np.testing.assert_allclose(ref_losses, zero_losses, rtol=2e-5)

    def test_save_group_sharded_model(self, tmp_path):
        from paddle_tpu.distributed.sharding import save_group_sharded_model

        m = make_model()
        opt = AdamW(parameters=m.parameters())
        m, opt, _ = group_sharded_parallel(m, opt, level="p_g_os")
        save_group_sharded_model(m._layers, str(tmp_path), opt)
        assert (tmp_path / "model.pdparams").exists()


class TestDygraphShardingOptimizer:
    def setup_method(self, _):
        set_mesh(build_mesh(sharding=8))

    def test_partition_and_step(self):
        from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer\
            .dygraph_sharding_optimizer import DygraphShardingOptimizer

        m = make_model()
        opt = DygraphShardingOptimizer(
            AdamW(learning_rate=1e-2, parameters=m.parameters()))
        parts = opt._partition_parameters()
        assert len(parts) == 8
        total = sum(len(v) for v in parts.values())
        assert total == len(list(m.parameters()))
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        losses = train_steps(m, opt, x)
        assert losses[-1] < losses[0]


class TestMemoryActuallyDrops:
    """VERDICT r2 weak-#8: placement must be PROVEN to cut per-device
    bytes, not just annotated — the memory_analysis() analog of the PP
    activation-bound test."""

    def _per_device_param_bytes(self, model):
        total, local = 0, 0
        for p in model.parameters():
            v = p._value
            total += v.size * v.dtype.itemsize
            local += max(s.data.size * s.data.dtype.itemsize
                         for s in v.addressable_shards)
        return local, total

    def test_stage3_params_at_rest_are_scattered(self):
        set_mesh(build_mesh(sharding=8))
        m = make_model()
        opt = AdamW(parameters=m.parameters())
        m2, opt2, _ = group_sharded_parallel(m, opt, level="p_g_os")
        local, total = self._per_device_param_bytes(m2._layers
                                                    if hasattr(m2, "_layers")
                                                    else m2)
        # weights divide 8 ways; biases (32, 8) divide too -> strictly 1/8
        assert local * 8 <= total * 1.01, (local, total)

    def test_stage2_opt_state_scattered_params_full(self):
        set_mesh(build_mesh(sharding=8))
        m = make_model()
        opt = AdamW(parameters=m.parameters())
        m2, opt2, _ = group_sharded_parallel(m, opt, level="os_g")
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 16).astype(np.float32))
        train_steps(m2, opt2, x, n=1)  # materialize opt state
        # moments are sharded 1/8 per device
        from paddle_tpu.core.tensor import Tensor

        st = getattr(opt2, "_accumulators", None) or {}
        seen = 0
        for name, per_param in st.items():
            for key, acc in per_param.items():
                # NOTE: isinstance check, not hasattr(_value) — jax's
                # ArrayImpl has an internal ._value (host buffer) too
                v = acc._value if isinstance(acc, Tensor) else acc
                if getattr(v, "ndim", 0) >= 1 and v.size % 8 == 0 and \
                        hasattr(v, "addressable_shards"):
                    local = max(s.data.size for s in v.addressable_shards)
                    if v.size >= 8:
                        assert local * 8 <= v.size * 1.01, (name, key)
                        seen += 1
        assert seen > 0, "no sharded accumulators found"

    def test_compiled_step_argument_bytes_scale(self):
        """The jitted train step's per-device argument footprint must drop
        ~1/N when params+moments carry the sharding placement."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def build(n_shard):
            mesh = build_mesh(sharding=n_shard, dp=8 // n_shard)
            H = 256
            w = jnp.zeros((H, H), jnp.float32)
            m = jnp.zeros((H, H), jnp.float32)
            spec = P("sharding") if n_shard > 1 else P()
            w = jax.device_put(w, NamedSharding(mesh, spec))
            m = jax.device_put(m, NamedSharding(mesh, spec))
            x = jnp.ones((4, H), jnp.float32)

            def step(w, m, x):
                g = jax.grad(lambda w: jnp.sum((x @ w) ** 2))(w)
                m2 = 0.9 * m + 0.1 * g
                return w - 0.01 * m2, m2

            c = jax.jit(step).lower(w, m, x).compile()
            ma = c.memory_analysis()
            return ma.argument_size_in_bytes

        a1, a8 = build(1), build(8)
        assert a8 * 4 < a1, (a8, a1)
