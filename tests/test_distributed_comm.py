"""Collective communication tests on an 8-device virtual CPU mesh.

Mirrors the reference pattern (test/legacy_test/test_collective_base.py):
numerical parity of each collective against numpy, in both calling contexts
(eager stacked-ranks and inside shard_map).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.topology import Group, build_mesh, set_mesh


def t2n(t):
    return np.asarray(t.numpy())


@pytest.fixture(autouse=True)
def _mesh():
    mesh = build_mesh(dp=4, mp=2)
    set_mesh(mesh)
    from paddle_tpu.distributed.communication import core

    core._reset_default_group()
    yield mesh


class TestEagerStacked:
    def test_all_reduce_sum(self, _mesh):
        g = Group("dp", _mesh)
        x = np.random.randn(4, 3, 5).astype(np.float32)
        t = paddle.to_tensor(x)
        dist.all_reduce(t, group=g)
        expected = np.broadcast_to(x.sum(0, keepdims=True), x.shape)
        np.testing.assert_allclose(t2n(t), expected, rtol=1e-5)

    def test_all_reduce_max_avg(self, _mesh):
        g = Group("dp", _mesh)
        x = np.random.randn(4, 6).astype(np.float32)
        t = paddle.to_tensor(x)
        dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(t2n(t), np.broadcast_to(x.max(0), (4, 6)))
        t = paddle.to_tensor(x)
        dist.all_reduce(t, op=dist.ReduceOp.AVG, group=g)
        np.testing.assert_allclose(t2n(t), np.broadcast_to(x.mean(0), (4, 6)),
                                   rtol=1e-6)

    def test_all_gather(self, _mesh):
        g = Group("mp", _mesh)
        x = np.random.randn(2, 3).astype(np.float32)
        out = []
        dist.all_gather(out, paddle.to_tensor(x), group=g)
        assert len(out) == 2
        np.testing.assert_allclose(t2n(out[0]), x[0])
        np.testing.assert_allclose(t2n(out[1]), x[1])

    def test_reduce_scatter(self, _mesh):
        g = Group("dp", _mesh)
        # each of 4 ranks holds [8] -> each gets sum of its 2-chunk
        x = np.random.randn(4, 8).astype(np.float32)
        out = dist.reduce_scatter(None, paddle.to_tensor(x), group=g)
        res = t2n(out.result)
        full = x.sum(0)
        for r in range(4):
            np.testing.assert_allclose(res[r], full[r * 2:(r + 1) * 2], rtol=1e-5)

    def test_all_to_all(self, _mesh):
        g = Group("dp", _mesh)
        x = np.arange(4 * 8, dtype=np.float32).reshape(4, 8)
        out = dist.all_to_all(None, paddle.to_tensor(x), group=g)
        res = t2n(out.result)
        # rank r, chunk j == rank j, chunk r
        xs = x.reshape(4, 4, 2)
        expected = np.swapaxes(xs, 0, 1).reshape(4, 8)
        np.testing.assert_allclose(res, expected)

    def test_broadcast(self, _mesh):
        g = Group("dp", _mesh)
        x = np.random.randn(4, 5).astype(np.float32)
        t = paddle.to_tensor(x)
        dist.broadcast(t, src=2, group=g)
        np.testing.assert_allclose(t2n(t), np.broadcast_to(x[2], (4, 5)))

    def test_reduce_to_dst(self, _mesh):
        g = Group("dp", _mesh)
        x = np.random.randn(4, 5).astype(np.float32)
        t = paddle.to_tensor(x)
        dist.reduce(t, dst=1, group=g)
        res = t2n(t)
        np.testing.assert_allclose(res[1], x.sum(0), rtol=1e-5)
        np.testing.assert_allclose(res[0], x[0])

    def test_scatter(self, _mesh):
        g = Group("dp", _mesh)
        parts = [paddle.to_tensor(np.full((3,), i, np.float32)) for i in range(4)]
        t = paddle.to_tensor(np.zeros((4, 3), np.float32))
        dist.scatter(t, parts, src=0, group=g)
        np.testing.assert_allclose(t2n(t), np.repeat(np.arange(4.0)[:, None], 3, 1))


class TestTracedContext:
    def test_psum_inside_shard_map(self, _mesh):
        def body(x):
            t = paddle.Tensor(x)
            dist.all_reduce(t, group=Group("dp", _mesh))
            return t.value

        f = shard_map(body, mesh=_mesh, in_specs=P("dp"), out_specs=P("dp"),
                      check_vma=False)
        x = np.random.randn(4, 6).astype(np.float32)
        out = jax.jit(f)(jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), np.broadcast_to(x.sum(0, keepdims=True), (4, 6)),
            rtol=1e-5)

    def test_all_gather_inside_shard_map(self, _mesh):
        def body(x):
            out = []
            dist.all_gather(out, paddle.Tensor(x[0]), group=Group("mp", _mesh))
            return jnp.stack([o.value for o in out])[None]

        f = shard_map(body, mesh=_mesh, in_specs=P("mp"), out_specs=P("mp"),
                      check_vma=False)
        x = np.random.randn(2, 3).astype(np.float32)
        out = np.asarray(jax.jit(f)(jnp.asarray(x)))
        # each shard sees the full stack
        np.testing.assert_allclose(out[0], x)
        np.testing.assert_allclose(out[1], x)


class TestTopology:
    def test_communicate_topology_math(self):
        topo = dist.CommunicateTopology(("data", "pipe", "model"), (2, 2, 2))
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm and [6, 7] in comm

    def test_hcg_over_mesh(self, _mesh):
        hcg = dist.HybridCommunicateGroup(mesh=_mesh)
        assert hcg.get_data_parallel_world_size() == 4
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 1
        assert hcg.get_model_parallel_group().nranks == 2

    def test_build_mesh_infers_dp(self):
        m = build_mesh(mp=2)
        assert m.shape["dp"] == 4 and m.shape["mp"] == 2
