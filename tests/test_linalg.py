"""paddle.linalg behavior-depth parity vs numpy (reference:
python/paddle/tensor/linalg.py + test/legacy_test/test_linalg_*).

Decomposition contracts (reconstruction, orthogonality), solver
residuals, norm order/axis/keepdim matrix, batched forms, and AD
spot-checks — the same depth-over-smoke treatment tests/test_fft.py
gives the fft module.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle

RTOL, ATOL = 2e-4, 2e-4


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def rand(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(
        np.float32)


def spd(n, seed=0):
    a = rand(n, n, seed=seed)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


class TestNorms:
    @pytest.mark.parametrize("p", [0, 1, 2, np.inf, -np.inf, 3.5])
    def test_vector_norm_orders(self, p):
        x = rand(6, seed=1)
        got = _np(paddle.linalg.norm(_t(x), p=p))
        np.testing.assert_allclose(got, np.linalg.norm(x, ord=p),
                                   rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("axis,keepdim", [(0, False), (1, True),
                                              (-1, False)])
    def test_vector_norm_axis(self, axis, keepdim):
        x = rand(4, 5, seed=2)
        got = _np(paddle.linalg.norm(_t(x), p=2, axis=axis,
                                     keepdim=keepdim))
        want = np.linalg.norm(x, axis=axis, keepdims=keepdim)
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("p", ["fro", 1, np.inf])
    def test_matrix_norms(self, p):
        x = rand(4, 5, seed=3)
        got = _np(paddle.linalg.norm(_t(x), p=p, axis=(-2, -1)))
        np.testing.assert_allclose(got, np.linalg.norm(x, ord=p),
                                   rtol=RTOL, atol=ATOL)


class TestDecompositions:
    def test_svd_reconstruction_and_modes(self):
        x = rand(5, 3, seed=4)
        for full in (False, True):
            u, s, vh = (paddle.linalg.svd(_t(x), full_matrices=full))
            u, s, vh = _np(u), _np(s), _np(vh)
            k = 3
            rec = (u[:, :k] * s) @ vh[:k] if full else (u * s) @ vh
            np.testing.assert_allclose(rec, x, rtol=1e-3, atol=1e-3)
            np.testing.assert_allclose(
                s, np.linalg.svd(x, compute_uv=False), rtol=1e-4,
                atol=1e-4)

    def test_qr_modes(self):
        x = rand(5, 3, seed=5)
        q, r = paddle.linalg.qr(_t(x), mode="reduced")
        q, r = _np(q), _np(r)
        assert q.shape == (5, 3) and r.shape == (3, 3)
        np.testing.assert_allclose(q @ r, x, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(q.T @ q, np.eye(3), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(r, np.triu(r), rtol=1e-5, atol=1e-5)
        q2, r2 = paddle.linalg.qr(_t(x), mode="complete")
        assert _np(q2).shape == (5, 5) and _np(r2).shape == (5, 3)
        np.testing.assert_allclose(_np(q2) @ _np(r2), x, rtol=1e-4,
                                   atol=1e-4)

    def test_eigh_symmetric(self):
        a = spd(4, seed=6)
        w, v = paddle.linalg.eigh(_t(a))
        w, v = _np(w), _np(v)
        np.testing.assert_allclose(a @ v, v * w, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.sort(w),
                                   np.sort(np.linalg.eigvalsh(a)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            _np(paddle.linalg.eigvalsh(_t(a))), w, rtol=1e-4, atol=1e-4)

    def test_eig_general(self):
        a = rand(4, 4, seed=7)
        w, v = paddle.linalg.eig(_t(a))
        w, v = _np(w), _np(v)
        np.testing.assert_allclose(a.astype(np.complex64) @ v, v * w,
                                   rtol=1e-3, atol=1e-3)
        got = np.sort_complex(_np(paddle.linalg.eigvals(_t(a))))
        np.testing.assert_allclose(got, np.sort_complex(np.linalg.eigvals(a)),
                                   rtol=1e-3, atol=1e-3)

    def test_cholesky_and_solve(self):
        a = spd(4, seed=8)
        b = rand(4, 2, seed=9)
        lo = _np(paddle.linalg.cholesky(_t(a), upper=False))
        np.testing.assert_allclose(lo @ lo.T, a, rtol=1e-3, atol=1e-3)
        up = _np(paddle.linalg.cholesky(_t(a), upper=True))
        np.testing.assert_allclose(up.T @ up, a, rtol=1e-3, atol=1e-3)
        x = _np(paddle.linalg.cholesky_solve(_t(b), _t(lo), upper=False))
        np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)

    def test_lu_unpack_reconstructs(self):
        a = rand(4, 4, seed=10)
        lu_t, piv, _ = paddle.linalg.lu(_t(a), get_infos=True)
        p, l, u = paddle.linalg.lu_unpack(lu_t, piv)
        rec = _np(p) @ _np(l) @ _np(u)
        np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)

    def test_householder_product_matches_qr_q(self):
        a = rand(5, 3, seed=11)
        # LAPACK geqrf gives the elementary-reflector form directly
        import scipy.linalg as sla

        (h, tau), _ = sla.qr(a, mode="raw")
        got = _np(paddle.linalg.householder_product(
            _t(np.ascontiguousarray(h).astype(np.float32)),
            _t(tau.astype(np.float32))))
        q_ref = sla.qr(a, mode="economic")[0]
        # columns are unique up to sign
        np.testing.assert_allclose(np.abs(got), np.abs(q_ref), rtol=1e-3,
                                   atol=1e-3)


class TestSolvers:
    def test_solve_batched(self):
        a = np.stack([spd(3, seed=s) for s in (12, 13)])
        b = rand(2, 3, 2, seed=14)
        x = _np(paddle.linalg.solve(_t(a), _t(b)))
        np.testing.assert_allclose(np.einsum("bij,bjk->bik", a, x), b,
                                   rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("upper,transpose", [(True, False),
                                                 (False, False),
                                                 (True, True)])
    def test_triangular_solve(self, upper, transpose):
        a = spd(4, seed=15)
        tri = np.triu(a) if upper else np.tril(a)
        b = rand(4, 2, seed=16)
        x = _np(paddle.linalg.triangular_solve(
            _t(tri), _t(b), upper=upper, transpose=transpose))
        m = tri.T if transpose else tri
        np.testing.assert_allclose(m @ x, b, rtol=1e-3, atol=1e-3)

    def test_lstsq_overdetermined(self):
        a = rand(6, 3, seed=17)
        b = rand(6, 2, seed=18)
        sol = paddle.linalg.lstsq(_t(a), _t(b))
        x = _np(sol[0])
        want = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(x, want, rtol=1e-3, atol=1e-3)

    def test_pinv_properties(self):
        a = rand(4, 3, seed=19)
        p = _np(paddle.linalg.pinv(_t(a)))
        np.testing.assert_allclose(a @ p @ a, a, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(p, np.linalg.pinv(a), rtol=1e-3,
                                   atol=1e-3)

    def test_inv_det_slogdet_batched(self):
        a = np.stack([spd(3, seed=s) for s in (20, 21)])
        np.testing.assert_allclose(_np(paddle.linalg.inv(_t(a))),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(_np(paddle.linalg.det(_t(a))),
                                   np.linalg.det(a), rtol=1e-3, atol=1e-1)
        sign, logdet = paddle.linalg.slogdet(_t(a))
        s_ref, l_ref = np.linalg.slogdet(a)
        np.testing.assert_allclose(_np(sign), s_ref, rtol=1e-5)
        np.testing.assert_allclose(_np(logdet), l_ref, rtol=1e-3,
                                   atol=1e-3)

    def test_matrix_power_negative(self):
        a = spd(3, seed=22)
        np.testing.assert_allclose(
            _np(paddle.linalg.matrix_power(_t(a), -2)),
            np.linalg.matrix_power(a, -2), rtol=1e-2, atol=1e-2)

    def test_matrix_rank_tol(self):
        a = rand(5, 3, seed=23)
        lowrank = a[:, :2] @ rand(2, 3, seed=24)   # rank 2
        assert int(_np(paddle.linalg.matrix_rank(_t(lowrank)))) == 2

    def test_cond_orders(self):
        a = spd(4, seed=25)
        for p in (None, 2, "fro"):
            got = float(_np(paddle.linalg.cond(_t(a), p=p)))
            want = float(np.linalg.cond(a, p=2 if p is None else p))
            np.testing.assert_allclose(got, want, rtol=1e-3)


class TestProductsAndStats:
    def test_multi_dot_matches_chain(self):
        ms = [rand(4, 5, seed=26), rand(5, 2, seed=27), rand(2, 6, seed=28)]
        got = _np(paddle.linalg.multi_dot([_t(m) for m in ms]))
        np.testing.assert_allclose(got, ms[0] @ ms[1] @ ms[2], rtol=1e-4,
                                   atol=1e-4)

    def test_cov_corrcoef(self):
        x = rand(3, 20, seed=29)
        np.testing.assert_allclose(_np(paddle.linalg.cov(_t(x))),
                                   np.cov(x), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(_np(paddle.linalg.corrcoef(_t(x))),
                                   np.corrcoef(x), rtol=1e-3, atol=1e-3)

    def test_cdist(self):
        from scipy.spatial.distance import cdist as scdist

        a, b = rand(4, 3, seed=30), rand(5, 3, seed=31)
        np.testing.assert_allclose(_np(paddle.linalg.cdist(_t(a), _t(b))),
                                   scdist(a, b), rtol=1e-3, atol=1e-3)

    def test_histogram_bincount(self):
        x = np.array([0, 1, 1, 3, 2, 1], np.int64)
        np.testing.assert_array_equal(
            _np(paddle.linalg.bincount(_t(x))), np.bincount(x))
        h = _np(paddle.linalg.histogram(_t(x.astype(np.float32)), bins=4,
                                        min=0, max=4))
        np.testing.assert_array_equal(h, np.histogram(
            x, bins=4, range=(0, 4))[0])


class TestGrads:
    def test_det_grad_is_det_times_invT(self):
        a = spd(3, seed=32)
        g = jax.grad(lambda m: jnp.linalg.det(m))(jnp.asarray(a))
        want = np.linalg.det(a) * np.linalg.inv(a).T
        np.testing.assert_allclose(np.asarray(g), want, rtol=1e-3,
                                   atol=1e-2)

    def test_solve_grad_numeric(self):
        a = spd(3, seed=33)
        b = rand(3, seed=34)

        def f(bv):
            x = paddle.linalg.solve(_t(a), paddle.to_tensor(bv))
            return (x * x).sum()

        bt = paddle.to_tensor(b)
        bt.stop_gradient = False
        x = paddle.linalg.solve(_t(a), bt)
        (x * x).sum().backward()
        g = _np(bt.grad)
        eps, num = 1e-3, np.zeros_like(b)
        for i in range(3):
            bp, bm = b.copy(), b.copy()
            bp[i] += eps
            bm[i] -= eps
            num[i] = (float(_np(f(bp))) - float(_np(f(bm)))) / (2 * eps)
        np.testing.assert_allclose(g, num, rtol=5e-2, atol=5e-2)
