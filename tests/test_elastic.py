"""Elastic fault-tolerance tests: REAL dead-peer detection + relaunch.

Reference contract (fleet/elastic/manager.py:120-124): the watch loop
detects a dead/new peer from heartbeat state and triggers a relaunch with a
regenerated rank map; ELASTIC_EXIT_CODE (:30) tells the launcher to
restart. These tests kill a real worker process and assert the survivor
notices, exits with the elastic code, and the launcher relaunches with
fresh dense ranks.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

native = pytest.importorskip("paddle_tpu.native")
try:
    _probe = native.TCPStoreServer(0)
    _probe.stop()
except Exception:  # pragma: no cover - no native lib in this env
    pytest.skip("native TCPStore unavailable", allow_module_level=True)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


WATCHER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)

    store = TCPStore("127.0.0.1", {port}, is_master=False, timeout=20)
    em = ElasticManager(store=store, np=2, heartbeat_interval=0.3,
                        dead_timeout=1.5)
    em.rank = {rank}
    em.register()
    deadline = time.time() + 30
    status = ElasticStatus.HOLD
    while time.time() < deadline:
        status = em.watch()
        if status != ElasticStatus.HOLD:
            break
        time.sleep(0.2)
    print("status", status, flush=True)
    sys.exit(em.exit(completed=(status == ElasticStatus.COMPLETED)))
""")

SLEEPER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.fleet.elastic import ElasticManager

    store = TCPStore("127.0.0.1", {port}, is_master=False, timeout=20)
    em = ElasticManager(store=store, np=2, heartbeat_interval=0.3,
                        dead_timeout=1.5)
    em.rank = {rank}
    em.register()
    print("registered", flush=True)
    time.sleep(120)
""")


@pytest.mark.slow
class TestDeadPeerDetection:
    def test_killed_worker_triggers_restart_exit(self):
        """Kill rank 1; rank 0's watch() must flip to RESTART and the
        process must exit ELASTIC_EXIT_CODE (101)."""
        from paddle_tpu.distributed.fleet.elastic import ELASTIC_EXIT_CODE
        from paddle_tpu.native import TCPStoreServer

        server = TCPStoreServer(0)
        try:
            env = dict(os.environ)
            a = subprocess.Popen(
                [sys.executable, "-c",
                 WATCHER.format(repo=REPO, port=server.port, rank=0)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            b = subprocess.Popen(
                [sys.executable, "-c",
                 SLEEPER.format(repo=REPO, port=server.port, rank=1)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            # wait until B registered (its first stdout line)
            line = b.stdout.readline()
            assert "registered" in line, line
            time.sleep(0.5)
            b.send_signal(signal.SIGKILL)
            out, err = a.communicate(timeout=30)
            assert "status restart" in out, (out, err)
            assert a.returncode == ELASTIC_EXIT_CODE, (a.returncode, err)
        finally:
            for p in (a, b):
                if p.poll() is None:
                    p.kill()
            server.stop()


class TestScaleUpDetection:
    def test_new_peer_join_triggers_restart(self):
        """A node registering after us bumps the join counter ->
        watch() == RESTART (scale-up path, manager.py PADDLE_ELASTIC_NP)."""
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        from paddle_tpu.native import TCPStore, TCPStoreServer

        server = TCPStoreServer(0)
        try:
            s1 = TCPStore("127.0.0.1", server.port)
            s2 = TCPStore("127.0.0.1", server.port)
            a = ElasticManager(store=s1, np=1, heartbeat_interval=0.2)
            a.rank = 0
            a.register()
            assert a.watch() == ElasticStatus.HOLD
            late = ElasticManager(store=s2, np=2, heartbeat_interval=0.2)
            late.rank = 1
            late.register()
            assert a.watch() == ElasticStatus.RESTART
            a.exit(completed=True)
            late.exit(completed=True)
        finally:
            server.stop()

    def test_completion_propagates(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        from paddle_tpu.native import TCPStore, TCPStoreServer

        server = TCPStoreServer(0)
        try:
            stores = [TCPStore("127.0.0.1", server.port) for _ in range(2)]
            ems = []
            for r, st in enumerate(stores):
                em = ElasticManager(store=st, np=2, heartbeat_interval=0.2)
                em.rank = r
                em.register()
                ems.append(em)
            assert ems[0].watch() == ElasticStatus.HOLD
            for em in ems:
                em.mark_done()
            assert ems[0].watch() == ElasticStatus.COMPLETED
            assert ems[1].watch() == ElasticStatus.COMPLETED
        finally:
            server.stop()


ELASTIC_WORKER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus,
                                                      rendezvous)

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    flag = {flag!r}
    results = {results!r}
    port = int(os.environ["MASTER_PORT"])
    # rank 0 owns the store server for this generation
    store = TCPStore("127.0.0.1", port, is_master=(rank == 0), timeout=30)
    gen = 1 if os.path.exists(flag) else 0
    my = rendezvous(store, gen, host="127.0.0.1")

    if gen == 0 and rank == 1:
        open(flag, "w").write("died")
        sys.exit(1)          # simulated hardware failure

    em = ElasticManager(store=store, np=2, heartbeat_interval=0.2,
                        dead_timeout=1.2)
    em.rank = rank
    em.register()
    if gen == 0:
        # survivor: watch until the dead peer is noticed, exit 101
        deadline = time.time() + 20
        while time.time() < deadline:
            if em.watch() == ElasticStatus.RESTART:
                sys.exit(em.exit(completed=False))
            time.sleep(0.1)
        sys.exit(3)          # detection failed
    # generation 1: both workers re-admitted with dense rendezvous ranks
    with open(results, "a") as f:
        f.write(f"{{gen}}:{{my}}\\n")
    sys.exit(em.exit(completed=True))
""")


@pytest.mark.slow
class TestLauncherRelaunch:
    def test_relaunch_readmits_survivor_with_fresh_ranks(self, tmp_path):
        """launch --elastic_level=1: gen-0 rank 1 dies; the launcher
        relaunches; gen-1 both workers rendezvous dense ranks {0, 1}."""
        from paddle_tpu.distributed.launch.main import launch

        flag = str(tmp_path / "died.flag")
        results = str(tmp_path / "ranks.txt")
        script = tmp_path / "worker.py"
        script.write_text(ELASTIC_WORKER.format(
            repo=REPO, flag=flag, results=results))
        port = _free_port()
        old_master = os.environ.get("PADDLE_MASTER")
        os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
        try:
            rc = launch(["--nproc_per_node", "2", "--elastic_level", "1",
                         "--max_restarts", "2", "--log_dir",
                         str(tmp_path / "log"), str(script)])
        finally:
            if old_master is None:
                os.environ.pop("PADDLE_MASTER", None)
            else:
                os.environ["PADDLE_MASTER"] = old_master
        assert rc == 0, rc
        lines = open(results).read().strip().splitlines()
        got = {tuple(l.split(":")) for l in lines}
        assert got == {("1", "0"), ("1", "1")}, lines
