"""paddle.metric vs sklearn — the reference metric semantics
(Accuracy top-k, binary Precision/Recall at 0.5, bucketed ROC AUC).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

sk = pytest.importorskip("sklearn.metrics")


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


class TestMetrics:
    def test_accuracy_topk(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(64, 5).astype(np.float32)
        labels = rng.randint(0, 5, (64, 1)).astype(np.int64)
        for k in (1, 2):
            m = paddle.metric.Accuracy(topk=(k,))
            m.update(m.compute(_t(logits), _t(labels)))
            got = float(np.asarray(m.accumulate()))
            top = np.argsort(-logits, axis=1)[:, :k]
            want = float(np.mean([labels[i, 0] in top[i]
                                  for i in range(64)]))
            np.testing.assert_allclose(got, want, rtol=1e-6,
                                       err_msg=f"top{k}")

    def test_precision_recall_binary(self):
        rng = np.random.RandomState(1)
        preds = rng.rand(200).astype(np.float32)
        labels = (rng.rand(200) < 0.4).astype(np.int64)
        p = paddle.metric.Precision()
        p.update(np.asarray(preds), labels)
        r = paddle.metric.Recall()
        r.update(np.asarray(preds), labels)
        hard = (preds > 0.5).astype(np.int64)
        np.testing.assert_allclose(float(p.accumulate()),
                                   sk.precision_score(labels, hard),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(r.accumulate()),
                                   sk.recall_score(labels, hard),
                                   rtol=1e-6)

    def test_auc_vs_sklearn(self):
        rng = np.random.RandomState(2)
        labels = (rng.rand(500) < 0.5).astype(np.int64)
        # informative scores so AUC is away from 0.5
        scores = (labels * 0.4 + rng.rand(500) * 0.8).clip(0, 1) \
            .astype(np.float32)
        preds = np.stack([1 - scores, scores], axis=1)
        m = paddle.metric.Auc(num_thresholds=4095)
        m.update(preds, labels[:, None])
        got = float(m.accumulate())
        want = float(sk.roc_auc_score(labels, scores))
        np.testing.assert_allclose(got, want, rtol=5e-3)
