"""Initializer contracts: fan computation, bounds, statistical moments,
and structural properties (orthogonality) — formula slips here silently
destroy training quality.
"""
import numpy as np

import paddle_tpu as paddle


def _collect(init, shape, n=40):
    outs = []
    for i in range(n):
        paddle.seed(1000 + i)
        p = paddle.create_parameter(shape=shape, dtype="float32",
                                    default_initializer=init)
        outs.append(np.asarray(p.value))
    return np.stack(outs)


class TestFanBased:
    def test_xavier_uniform_bound(self):
        # bound = sqrt(6/(fan_in+fan_out)); [in=80, out=120] -> ~0.1732
        s = _collect(paddle.nn.initializer.XavierUniform(), [80, 120])
        bound = np.sqrt(6.0 / 200.0)
        assert s.max() <= bound + 1e-6 and s.min() >= -bound - 1e-6
        assert s.max() > bound * 0.98        # actually fills the range
        np.testing.assert_allclose(s.std(), bound / np.sqrt(3), rtol=0.05)

    def test_xavier_normal_std(self):
        s = _collect(paddle.nn.initializer.XavierNormal(), [80, 120])
        np.testing.assert_allclose(s.std(), np.sqrt(2.0 / 200.0),
                                   rtol=0.05)

    def test_kaiming_normal_fan_in(self):
        # std = sqrt(2/fan_in) (relu gain); fan_in = 90
        s = _collect(paddle.nn.initializer.KaimingNormal(), [90, 60])
        np.testing.assert_allclose(s.std(), np.sqrt(2.0 / 90.0),
                                   rtol=0.05)

    def test_kaiming_uniform_bound(self):
        s = _collect(paddle.nn.initializer.KaimingUniform(), [90, 60])
        bound = np.sqrt(6.0 / 90.0)
        assert s.max() <= bound + 1e-6 and s.min() >= -bound - 1e-6

    def test_conv_fan_includes_receptive_field(self):
        # conv weight [out, in, kh, kw]: fan_in = in*kh*kw = 4*3*3 = 36
        s = _collect(paddle.nn.initializer.KaimingNormal(), [8, 4, 3, 3])
        np.testing.assert_allclose(s.std(), np.sqrt(2.0 / 36.0),
                                   rtol=0.06)


class TestStructural:
    def test_orthogonal_rows(self):
        paddle.seed(7)
        p = paddle.create_parameter(
            shape=[16, 64], dtype="float32",
            default_initializer=paddle.nn.initializer.Orthogonal())
        w = np.asarray(p.value)
        np.testing.assert_allclose(w @ w.T, np.eye(16), atol=1e-4)

    def test_dirac_identity_conv(self):
        paddle.seed(8)
        p = paddle.create_parameter(
            shape=[4, 4, 3, 3], dtype="float32",
            default_initializer=paddle.nn.initializer.Dirac())
        w = np.asarray(p.value)
        # center tap is identity across channels, everything else zero
        assert np.allclose(w[:, :, 1, 1], np.eye(4))
        w2 = w.copy()
        w2[:, :, 1, 1] = 0
        assert np.allclose(w2, 0)

    def test_truncated_normal_respects_bounds(self):
        s = _collect(paddle.nn.initializer.TruncatedNormal(std=1.0),
                     [50, 50], n=10)
        assert np.abs(s).max() <= 2.0 + 1e-5   # +-2 std truncation
        assert np.abs(s).max() > 1.5           # not silently clipped small
