"""paddle.utils tests (reference utils/__init__ surface + unique_name /
dlpack / download submodules)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import utils


class TestTopLevel:
    def test_deprecated_warns_and_works(self):
        @utils.deprecated(update_to="paddle.new_op", since="2.0")
        def old_op(x):
            return x + 1

        with pytest.warns(DeprecationWarning, match="new_op"):
            assert old_op(1) == 2

    def test_deprecated_level2_raises(self):
        @utils.deprecated(level=2)
        def gone():
            pass

        with pytest.raises(RuntimeError, match="deprecated"):
            gone()

    def test_require_version(self):
        assert utils.require_version("0.0.1") is True
        with pytest.raises(Exception, match="minimum"):
            utils.require_version("999.0.0")
        with pytest.raises(Exception, match="maximum"):
            utils.require_version("0.0.1", "0.0.2")

    def test_try_import(self):
        assert utils.try_import("math").sqrt(4) == 2
        with pytest.raises(ImportError, match="no_such_mod"):
            utils.try_import("no_such_mod")

    def test_run_check(self, capsys):
        utils.run_check()
        out = capsys.readouterr().out
        assert "works on" in out


class TestUniqueName:
    def test_generate_monotonic(self):
        a = utils.unique_name.generate("fc")
        b = utils.unique_name.generate("fc")
        assert a != b and a.startswith("fc_") and b.startswith("fc_")

    def test_guard_scopes(self):
        with utils.unique_name.guard():
            x = utils.unique_name.generate("w")
        with utils.unique_name.guard():
            y = utils.unique_name.generate("w")
        assert x == y == "w_0"   # fresh scope restarts numbering

    def test_guard_prefix(self):
        with utils.unique_name.guard("block1_"):
            n = utils.unique_name.generate("w")
        assert n == "block1_w_0"


class TestDlpack:
    def test_roundtrip_with_torch(self):
        torch = pytest.importorskip("torch")
        t = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
        cap = utils.dlpack.to_dlpack(t)
        tt = torch.utils.dlpack.from_dlpack(cap)
        np.testing.assert_allclose(tt.numpy(), [1.0, 2.0, 3.0])
        back = utils.dlpack.from_dlpack(torch.tensor([4.0, 5.0]))
        np.testing.assert_allclose(np.asarray(back.value), [4.0, 5.0])


class TestDownload:
    def test_cache_hit(self, tmp_path):
        f = tmp_path / "w.pdparams"
        f.write_bytes(b"x")
        got = utils.download.get_path_from_url(
            "http://example.invalid/w.pdparams", str(tmp_path))
        assert got == str(f)

    def test_cache_miss_actionable(self, tmp_path):
        with pytest.raises(RuntimeError, match="pre-seed"):
            utils.download.get_path_from_url(
                "http://example.invalid/missing.bin", str(tmp_path))
