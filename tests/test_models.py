"""Model-family tests: GPT, vision zoo beyond ResNet.

Reference analogs: test/legacy_test/test_vision_models.py,
gpt model coverage in the fleet/hybrid tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

# The compile-heavy classes (vision zoo sweep, GPT fit loop, remat
# grad sweep) ride the slow tier — moved when the prefix-cache suite
# (round 11) pushed tier-1 against its 870s timeout. A GPT forward
# smoke and the op-tail checks stay tier-1 so a model-path regression
# still fails the default run.


class TestGPT:
    def _model(self):
        from paddle_tpu.models import GPTForCausalLM, gpt_config

        cfg = gpt_config("tiny")
        return GPTForCausalLM(cfg), cfg

    def test_forward_shape_and_loss(self):
        m, cfg = self._model()
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)
        logits = m(paddle.to_tensor(ids))
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        labels = np.random.RandomState(1).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)
        loss = m(paddle.to_tensor(ids), paddle.to_tensor(labels))
        assert np.isfinite(float(loss.numpy()))

    @pytest.mark.slow
    def test_train_step_reduces_loss(self):
        from paddle_tpu import optimizer as opt

        m, cfg = self._model()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        rng = np.random.RandomState(2)
        ids = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        losses = []
        for _ in range(8):
            loss = m(paddle.to_tensor(ids), paddle.to_tensor(labels))
            losses.append(float(loss.numpy()))
            loss.backward()
            o.step()
            o.clear_grad()
        assert losses[-1] < losses[0]


@pytest.mark.slow
class TestVisionZoo:
    @pytest.mark.parametrize("name", ["mobilenet_v2", "squeezenet1_0",
                                      "vgg11", "alexnet"])
    def test_forward_shapes(self, name):
        import paddle_tpu.vision.models as vm

        model = getattr(vm, name)(num_classes=10)
        model.eval()
        x = np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32)
        out = model(paddle.to_tensor(x))
        assert tuple(out.shape) == (2, 10)
        assert np.all(np.isfinite(np.asarray(out.numpy())))


class TestOpTail2:
    def test_index_fill(self):
        rng = np.random.RandomState(0)
        x = rng.rand(5, 4).astype(np.float32)
        out = paddle.index_fill(paddle.to_tensor(x),
                                paddle.to_tensor(np.asarray([0, 2])), 0, -1.0)
        o = np.asarray(out.numpy())
        assert np.all(o[[0, 2]] == -1.0)
        np.testing.assert_allclose(o[[1, 3, 4]], x[[1, 3, 4]])
        # axis=1
        out = paddle.index_fill(paddle.to_tensor(x),
                                paddle.to_tensor(np.asarray([1])), 1, 7.0)
        assert np.all(np.asarray(out.numpy())[:, 1] == 7.0)

    def test_index_fill_inplace(self):
        x = paddle.to_tensor(np.zeros((3, 3), np.float32))
        r = paddle.index_fill_(x, paddle.to_tensor(np.asarray([2])), 0, 5.0)
        assert np.all(np.asarray(x.numpy())[2] == 5.0)
        assert r is x

    def test_householder_product_matches_qr(self):
        import scipy.linalg

        rng = np.random.RandomState(1)
        a = rng.rand(6, 4).astype(np.float64)
        (h64, tau), _r = scipy.linalg.qr(a, mode="raw")
        h = np.asarray(h64, np.float32)
        q = paddle.linalg.householder_product(
            paddle.to_tensor(h), paddle.to_tensor(tau.astype(np.float32)))
        q_ref = np.linalg.qr(a)[0]
        np.testing.assert_allclose(np.asarray(q.numpy()), q_ref.astype(
            np.float32), atol=1e-4)


@pytest.mark.slow
class TestRematPolicies:
    """remat="attn_out" (save_only_these_names over the flash output,
    llama_functional._remat_policy) must be grad-exact vs full remat and
    no-remat — it changes WHAT is recomputed, never the math."""

    def test_remat_modes_grad_exact(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models import LlamaForCausalLM, llama_config
        from paddle_tpu.models.llama_functional import (build_loss_fn,
                                                        stack_params)

        cfg = llama_config("tiny")
        m = LlamaForCausalLM(cfg)
        params = {k: p.value for k, p in m.named_parameters()}
        stacked, rest = stack_params(params, cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 32)), jnp.int32)
        y = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 32)), jnp.int32)
        outs = {}
        for mode in (True, "attn_out", "none"):
            lf = build_loss_fn(cfg, remat=mode)
            loss, g = jax.jit(jax.value_and_grad(
                lambda s, _lf=lf: _lf(s, rest, ids, y)))(stacked)
            outs[mode] = (float(loss), g)
        l0, g0 = outs[True]
        for mode in ("attn_out", "none"):
            l1, g1 = outs[mode]
            assert l1 == pytest.approx(l0, abs=1e-6)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
                g0, g1)
