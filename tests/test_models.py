"""Model-family tests: GPT, vision zoo beyond ResNet.

Reference analogs: test/legacy_test/test_vision_models.py,
gpt model coverage in the fleet/hybrid tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestGPT:
    def _model(self):
        from paddle_tpu.models import GPTForCausalLM, gpt_config

        cfg = gpt_config("tiny")
        return GPTForCausalLM(cfg), cfg

    def test_forward_shape_and_loss(self):
        m, cfg = self._model()
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)
        logits = m(paddle.to_tensor(ids))
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        labels = np.random.RandomState(1).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)
        loss = m(paddle.to_tensor(ids), paddle.to_tensor(labels))
        assert np.isfinite(float(loss.numpy()))

    def test_train_step_reduces_loss(self):
        from paddle_tpu import optimizer as opt

        m, cfg = self._model()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        rng = np.random.RandomState(2)
        ids = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        losses = []
        for _ in range(8):
            loss = m(paddle.to_tensor(ids), paddle.to_tensor(labels))
            losses.append(float(loss.numpy()))
            loss.backward()
            o.step()
            o.clear_grad()
        assert losses[-1] < losses[0]


class TestVisionZoo:
    @pytest.mark.parametrize("name", ["mobilenet_v2", "squeezenet1_0",
                                      "vgg11", "alexnet"])
    def test_forward_shapes(self, name):
        import paddle_tpu.vision.models as vm

        model = getattr(vm, name)(num_classes=10)
        model.eval()
        x = np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32)
        out = model(paddle.to_tensor(x))
        assert tuple(out.shape) == (2, 10)
        assert np.all(np.isfinite(np.asarray(out.numpy())))
