"""Pipeline-parallel tests.

Mirrors the reference PP test contract (SURVEY.md §4.2,
test/collective/fleet/hybrid_parallel_pp_transformer.py): the pipelined run
must match the serial baseline numerically, and the schedule must train.
"""
import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
    PipelineParallel, PipelineParallelWithInterleave)
from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
    build_pipeline_loss_fn, build_pipeline_train_step)
from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
    LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc)
from paddle_tpu.distributed.topology import build_mesh, set_mesh

V, H, S = 32, 16, 8


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


class Embed(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(V, H)

    def forward(self, ids):
        return self.emb(ids)


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, V)

    def forward(self, x):
        return self.fc(x)


def loss_fn(out, y):
    return nn.functional.cross_entropy(out.reshape([-1, V]), y.reshape([-1]))


def make_pipe(num_stages=4, **kw):
    descs = ([LayerDesc(Embed)] + [LayerDesc(Block) for _ in range(6)]
             + [LayerDesc(Head)])
    return PipelineLayer(descs, num_stages=num_stages, loss_fn=loss_fn, **kw)


def batch(n=8):
    rng = np.random.RandomState(7)
    ids = rng.randint(0, V, (n, S)).astype(np.int32)
    y = rng.randint(0, V, (n, S)).astype(np.int32)
    return ids, y


class TestSegmentLayers:
    def test_uniform(self):
        assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]
        assert SegmentLayers.uniform(10, 4) == [0, 3, 6, 8, 10]

    def test_manual(self):
        descs = [LayerDesc(Block) for _ in range(8)]
        seg = SegmentLayers(descs, 2, method=[0, 3, 8])
        assert seg.do_segment() == [0, 3, 8]

    def test_by_layer_name(self):
        descs = ([LayerDesc(Embed)] + [LayerDesc(Block) for _ in range(4)]
                 + [LayerDesc(Head)])
        seg = SegmentLayers(descs, 2, method="layer:Block")
        parts = seg.do_segment()
        assert parts[0] == 0 and parts[-1] == 6 and len(parts) == 3

    def test_too_few_layers(self):
        with pytest.raises(ValueError):
            SegmentLayers([LayerDesc(Block)], 2).do_segment()


class TestPipelineLayerSerial:
    def test_stage_tagging(self):
        pipe = make_pipe(4)
        assert pipe.segment_parts == [0, 2, 4, 6, 8]
        stages = {pipe.get_stage_from_index(i) for i in range(8)}
        assert stages == {0, 1, 2, 3}
        for _, p in pipe.named_parameters():
            assert hasattr(p, "pp_stage")

    def test_serial_forward_matches_plain(self):
        pipe = make_pipe(4)
        ids, y = batch()
        out = pipe(paddle.Tensor(ids))
        # same layers run manually
        x = paddle.Tensor(ids)
        for layer in pipe.run_function:
            x = layer(x)
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)

    def test_recompute_interval_matches(self):
        pipe = make_pipe(4)
        ids, y = batch()
        ref = pipe(paddle.Tensor(ids))
        pipe._recompute_interval = 2
        out = pipe(paddle.Tensor(ids))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)

    def test_shared_layer_desc(self):
        def tied_head(shared, x):
            return paddle.matmul(x, shared.emb.weight, transpose_y=True)

        descs = ([SharedLayerDesc("emb", Embed)]
                 + [LayerDesc(Block) for _ in range(2)]
                 + [SharedLayerDesc("emb", Embed, forward_func=tied_head)])
        pipe = PipelineLayer(descs, num_stages=2, loss_fn=loss_fn)
        assert "emb" in pipe.shared_layers
        ids, y = batch()
        out = pipe(paddle.Tensor(ids))
        assert tuple(out.shape) == (8, S, V)
        # tied grads: backward accumulates both uses into ONE weight
        loss = loss_fn(out, paddle.Tensor(y))
        loss.backward()
        emb_w = pipe.shared_layers["emb"].emb.weight
        assert emb_w.grad is not None


class TestEagerSchedule:
    def test_train_batch_matches_serial_grad_accum(self):
        from paddle_tpu.distributed.fleet.base.distributed_strategy import (
            DistributedStrategy)
        from paddle_tpu.optimizer import SGD

        ids, y = batch()
        # serial baseline: full-batch loss and one SGD step
        pipe_ref = make_pipe(1)
        sd = pipe_ref.state_dict()
        out = pipe_ref(paddle.Tensor(ids))
        ref_loss = loss_fn(out, paddle.Tensor(y))

        pipe = make_pipe(4)
        pipe.set_state_dict(sd)
        strat = DistributedStrategy()
        strat.pipeline_configs["accumulate_steps"] = 4
        pp = PipelineParallel(pipe, strategy=strat)
        opt = SGD(learning_rate=0.1, parameters=pipe.parameters())
        loss = pp.train_batch((paddle.Tensor(ids), paddle.Tensor(y)), opt)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)

        # params actually moved
        sd2 = pipe.state_dict()
        moved = any(
            not np.allclose(sd[k].numpy(), sd2[k].numpy()) for k in sd)
        assert moved

    def test_eval_batch(self):
        pipe = make_pipe(4)
        ids, y = batch()
        pp = PipelineParallel(pipe)
        loss = pp.eval_batch((paddle.Tensor(ids), paddle.Tensor(y)))
        assert np.isfinite(float(loss))

    def test_interleave_requires_chunks(self):
        pipe = make_pipe(2)
        with pytest.raises(ValueError):
            PipelineParallelWithInterleave(pipe)

    def test_interleave_chunk_mapping(self):
        descs = [LayerDesc(Block) for _ in range(8)]
        pipe = PipelineLayer(descs, num_stages=2, loss_fn=loss_fn,
                             num_virtual_pipeline_stages=2)
        assert pipe.total_chunks == 4
        pp = PipelineParallelWithInterleave(pipe)
        # forward chunk order on a stage cycles 0,0,1,1 then back
        assert pp._get_virtual_pp_rank(0) == 0
        assert pp._get_virtual_pp_rank(2) == 1
        assert pp._get_virtual_pp_rank(0, forward=False) == 1


class TestCompiledPipeline:
    def setup_method(self, _):
        self.mesh = build_mesh(pp=4, dp=2)
        set_mesh(self.mesh)

    def test_pipelined_loss_matches_serial(self):
        pipe = make_pipe(4)
        ids, y = batch()
        out = pipe(paddle.Tensor(ids))
        ref = float(loss_fn(out, paddle.Tensor(y)))
        params = {k: p.value for k, p in pipe.named_parameters()}
        plf = build_pipeline_loss_fn(pipe, accumulate_steps=4, mesh=self.mesh)
        got = float(jax.jit(plf)(params, ids, y))
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_train_step_reduces_loss(self):
        pipe = make_pipe(4)
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        step, init = build_pipeline_train_step(
            pipe, accumulate_steps=4, mesh=self.mesh, lr=1e-2)
        st = init(params)
        p, st, l0 = step(params, st, ids, y)
        for _ in range(3):
            p, st, l = step(p, st, ids, y)
        assert float(l) < float(l0)

    def test_remat_matches(self):
        pipe = make_pipe(4)
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        plf = build_pipeline_loss_fn(pipe, accumulate_steps=4,
                                     mesh=self.mesh, remat=True)
        plain = build_pipeline_loss_fn(pipe, accumulate_steps=4,
                                       mesh=self.mesh)
        np.testing.assert_allclose(
            float(jax.jit(plf)(params, ids, y)),
            float(jax.jit(plain)(params, ids, y)), rtol=1e-5)

    def test_no_pp_axis_runs_all_stages(self):
        # mesh without pp: serial path must still compose every stage
        mesh = build_mesh(dp=8)
        pipe = make_pipe(4)
        ids, y = batch()
        out = pipe(paddle.Tensor(ids))
        ref = float(loss_fn(out, paddle.Tensor(y)))
        params = {k: p.value for k, p in pipe.named_parameters()}
        plf = build_pipeline_loss_fn(pipe, accumulate_steps=4, mesh=mesh)
        got = float(jax.jit(plf)(params, ids, y))
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_stage_mesh_mismatch_raises(self):
        mesh = build_mesh(pp=2, dp=4)
        pipe = make_pipe(4)
        with pytest.raises(ValueError, match="segmented"):
            build_pipeline_loss_fn(pipe, accumulate_steps=4, mesh=mesh)

    def test_grads_match_serial(self):
        pipe = make_pipe(4)
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}

        def serial(params, ids, y):
            from paddle_tpu.nn.functional_call import functional_call

            out = functional_call(pipe, params, paddle.Tensor(ids))
            import jax.numpy as jnp

            lbl = y.reshape(-1)
            logits = out.reshape((-1, V))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.mean(jnp.take_along_axis(logp, lbl[:, None], 1))

        g_ref = jax.grad(serial)(params, ids, y)
        plf = build_pipeline_loss_fn(pipe, accumulate_steps=4, mesh=self.mesh)
        g_pp = jax.jit(jax.grad(plf))(params, ids, y)
        for k in g_ref:
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_ref[k]), atol=2e-5,
                err_msg=k)


class Test1F1B:
    """True 1F1B schedule: grad parity with serial, interleaved virtual
    stages (reference pipeline_parallel.py:804), and the memory contract —
    live activations bounded by pipeline depth, not microbatch count."""

    def setup_method(self, _):
        self.mesh = build_mesh(pp=4, dp=2)
        set_mesh(self.mesh)

    @staticmethod
    def _serial_ref(pipe):
        def serial(params, ids, y):
            from paddle_tpu.nn.functional_call import functional_call
            import jax.numpy as jnp

            out = functional_call(pipe, params, paddle.Tensor(ids))
            lbl = y.reshape(-1)
            logits = out.reshape((-1, V))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.mean(jnp.take_along_axis(logp, lbl[:, None], 1))

        return serial

    def test_1f1b_grads_match_serial(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
            build_pipeline_1f1b_grad_fn)

        pipe = make_pipe(4)
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        l_ref, g_ref = jax.value_and_grad(self._serial_ref(pipe))(
            params, ids, y)
        gf = build_pipeline_1f1b_grad_fn(pipe, accumulate_steps=4,
                                         mesh=self.mesh)
        l_pp, g_pp = jax.jit(gf)(params, ids, y)
        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-4)
        for k in g_ref:
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_ref[k]), atol=2e-5,
                err_msg=k)

    def test_1f1b_interleaved_grads_match_serial(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
            build_pipeline_1f1b_grad_fn)

        mesh = build_mesh(pp=2, dp=4)
        pipe = make_pipe(2, num_virtual_pipeline_stages=2)
        assert pipe.total_chunks == 4
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        l_ref, g_ref = jax.value_and_grad(self._serial_ref(pipe))(
            params, ids, y)
        gf = build_pipeline_1f1b_grad_fn(pipe, accumulate_steps=4, mesh=mesh)
        l_pp, g_pp = jax.jit(gf)(params, ids, y)
        np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-4)
        for k in g_ref:
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_ref[k]), atol=2e-5,
                err_msg=k)

    def test_interleaved_forward_loss_matches_serial(self):
        mesh = build_mesh(pp=2, dp=4)
        pipe = make_pipe(2, num_virtual_pipeline_stages=2)
        ids, y = batch()
        out = pipe(paddle.Tensor(ids))
        ref = float(loss_fn(out, paddle.Tensor(y)))
        params = {k: p.value for k, p in pipe.named_parameters()}
        plf = build_pipeline_loss_fn(pipe, accumulate_steps=4, mesh=mesh)
        got = float(jax.jit(plf)(params, ids, y))
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_1f1b_train_step_reduces_loss(self):
        pipe = make_pipe(4)
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        step, init = build_pipeline_train_step(
            pipe, accumulate_steps=4, mesh=self.mesh, lr=1e-2,
            schedule="1f1b")
        st = init(params)
        p, st, l0 = step(params, st, ids, y)
        for _ in range(3):
            p, st, l = step(p, st, ids, y)
        assert float(l) < float(l0)

    def test_interleave_rejects_indivisible_microbatches(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
            build_pipeline_1f1b_grad_fn)

        mesh = build_mesh(pp=2, dp=4)
        pipe = make_pipe(2, num_virtual_pipeline_stages=2)
        with pytest.raises(ValueError, match="divisible"):
            build_pipeline_1f1b_grad_fn(pipe, accumulate_steps=3, mesh=mesh)

    def test_1f1b_activation_memory_bounded_by_depth(self):
        """Doubling M must NOT double 1F1B temp memory (it does for GPipe
        without remat — that's the memory profile 1F1B exists to avoid)."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
            build_pipeline_1f1b_grad_fn)

        pipe = make_pipe(4)
        params = {k: p.value for k, p in pipe.named_parameters()}

        def temp_bytes(fn, m):
            ids, y = batch(m * 2)  # microbatch size 2
            c = jax.jit(fn).lower(params, ids, y).compile()
            ma = c.memory_analysis()
            return ma.temp_size_in_bytes

        def f1(m):
            return build_pipeline_1f1b_grad_fn(pipe, m, mesh=self.mesh)

        def fg(m):
            return jax.value_and_grad(
                build_pipeline_loss_fn(pipe, m, mesh=self.mesh))

        t8, t32 = temp_bytes(f1(8), 8), temp_bytes(f1(32), 32)
        g8, g32 = temp_bytes(fg(8), 8), temp_bytes(fg(32), 32)
        # GPipe grows ~linearly in M; 1F1B must grow far slower
        gpipe_growth = g32 / max(g8, 1)
        f1b_growth = t32 / max(t8, 1)
        assert f1b_growth < 2.0, (f1b_growth, gpipe_growth)
        assert f1b_growth < gpipe_growth * 0.75, (f1b_growth, gpipe_growth)

    def test_1f1b_dropout_fwd_bwd_masks_consistent(self):
        """With dropout in the pipe, 1F1B's backward remat must replay the
        SAME masks as forward — finite differences of the returned loss must
        match the returned grads (they can't if masks diverge)."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
            build_pipeline_1f1b_grad_fn)

        class DropBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(H, H)

            def forward(self, x):
                return nn.functional.dropout(
                    paddle.tanh(self.fc(x)), p=0.3, training=True)

        descs = ([LayerDesc(Embed)] + [LayerDesc(DropBlock) for _ in range(6)]
                 + [LayerDesc(Head)])
        pipe = PipelineLayer(descs, num_stages=4, loss_fn=loss_fn)
        pipe.train()
        ids, y = batch()
        params = {k: p.value for k, p in pipe.named_parameters()}
        gf = jax.jit(build_pipeline_1f1b_grad_fn(pipe, accumulate_steps=4,
                                                 mesh=self.mesh))
        l0, g = gf(params, ids, y)
        key = "run_function.1.fc.weight"
        eps = 1e-3
        idx = (3, 5)
        pp_ = dict(params)
        pp_[key] = params[key].at[idx].add(eps)
        lp, _ = gf(pp_, ids, y)
        pp_[key] = params[key].at[idx].add(-eps)
        lm, _ = gf(pp_, ids, y)
        fd = (float(lp) - float(lm)) / (2 * eps)
        assert abs(fd - float(g[key][idx])) < 5e-3, (fd, float(g[key][idx]))
