"""Cross-process fleet: HTTP RemoteReplica + disaggregated prefill/decode.

ISSUE-17 acceptance on CPU: a 2-subprocess fleet (1 prefill + 1 decode
replica, ``JAX_PLATFORMS=cpu``) serves a streamed request end-to-end
with KV pages shipped via ``POST /kv/export`` → ``POST /kv/import``,
BYTE-IDENTICAL to the monolithic engine on the same prompt; the
handoff is idempotent (chain-hash dedup on re-ship); failover replay
succeeds when the decode replica is KILLED mid-stream (replayed on the
prefill replica, whose pages never left). Plus the satellites: the
Router consumes :class:`RemoteReplica` through its unmodified
duck-typed seam (process-kill failover with byte parity), wire-format
round-trips (``LatencyDigest``/``_ProgramRecord``
``to_dict → HTTP → from_dict → fleet_rollup``) across a REAL process
boundary with merge-exact (never averaged) fleet percentiles, and the
strict-body 400 class extended to the ``/kv`` endpoints and the
non-bool ``stream`` field.
"""
import http.client
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.generation import (
    GenerationConfig, PagedContinuousBatchingEngine)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.serving import (RequestFailed, Router, Server)
from paddle_tpu.serving.remote import (
    DisaggregatedFront, RemoteReplica, RemoteReplicaSpec,
    decode_kv_payload, encode_kv_payload, spawn_replica)

CFG = llama_config("tiny", num_hidden_layers=2)
PROMPT = list(range(1, 18))        # 17 tokens -> 2 FULL blocks of 8
REPLICA_ARGS = ["--layers", "2", "--num-pages", "32",
                "--page-size", "8", "--max-pages", "8",
                "--max-batch", "2", "--segment-steps", "2"]
REPLICA_ENV = {"FLAGS_enable_monitor": "1", "FLAGS_enable_ledger": "1"}


def make_engine(**kw):
    paddle.seed(0)                 # deterministic init: every process
    #                                holds bitwise-identical weights
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 32)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages", 8)
    kw.setdefault("prefix_cache", True)
    return PagedContinuousBatchingEngine(LlamaForCausalLM(CFG), **kw)


@pytest.fixture(scope="module")
def ref_server():
    """The monolithic reference — byte-identity bar for every
    cross-process path."""
    srv = Server(make_engine(), segment_steps=2, idle_wait_s=0.005)
    yield srv
    srv.shutdown(drain=False)


@pytest.fixture(scope="module")
def ref24(ref_server):
    h = ref_server.submit(np.asarray(PROMPT, np.int32),
                          GenerationConfig(max_new_tokens=24))
    return [int(t) for t in h.result(timeout=180)]


@pytest.fixture(scope="module")
def fleet():
    """One shared 2-subprocess fleet: (prefill, decode) replicas with
    identical seeded weights, monitor + ledger enabled."""
    p1, u1 = spawn_replica(REPLICA_ARGS, env=REPLICA_ENV)
    p2, u2 = spawn_replica(REPLICA_ARGS, env=REPLICA_ENV)
    pre = RemoteReplica(u1, proc=p1)
    dec = RemoteReplica(u2, proc=p2)
    assert pre.wait_ready(120) and dec.wait_ready(120)
    yield pre, dec
    pre.shutdown(drain=False)
    dec.shutdown(drain=False)


def _post(url, path, body, ctype="application/json"):
    """One raw request against a replica URL; returns (status, dict)."""
    from urllib.parse import urlsplit

    u = urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    try:
        raw = (body if isinstance(body, bytes)
               else json.dumps(body).encode())
        conn.request("POST", path, body=raw,
                     headers={"Content-Type": ctype})
        resp = conn.getresponse()
        data = resp.read()
        try:
            return resp.status, json.loads(data)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return resp.status, {"raw": data}
    finally:
        conn.close()


class TestKVWireFormat:
    """encode_kv_payload/decode_kv_payload framing: exact array
    round-trip (bf16 AND int8+scales), exhaustive validation of
    untrusted bytes."""

    @staticmethod
    def _payload(kv_dtype="bf16"):
        import ml_dtypes

        dt = (np.dtype(ml_dtypes.bfloat16) if kv_dtype == "bf16"
              else np.int8)
        rng = np.random.default_rng(0)
        lay = {"k": rng.standard_normal((2, 8, 4)).astype(dt),
               "v": rng.standard_normal((2, 8, 4)).astype(dt)}
        if kv_dtype == "int8":
            lay["k_scale"] = rng.standard_normal(
                (2, 8)).astype(np.float32)
            lay["v_scale"] = rng.standard_normal(
                (2, 8)).astype(np.float32)
        return {"version": 1, "kv_dtype": kv_dtype, "page_size": 8,
                "salt": "", "coverage": 16,
                "blocks": [{"hash": "aa", "parent": None,
                            "tokens": list(range(8))},
                           {"hash": "bb", "parent": "aa",
                            "tokens": list(range(8, 16))}],
                "layers": [lay]}

    @pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
    def test_round_trip_exact(self, kv_dtype):
        p = self._payload(kv_dtype)
        out = decode_kv_payload(encode_kv_payload(p))
        assert out["kv_dtype"] == kv_dtype
        assert out["blocks"] == p["blocks"]
        assert out["coverage"] == 16
        for key, arr in p["layers"][0].items():
            got = out["layers"][0][key]
            assert got.dtype == arr.dtype
            assert got.shape == arr.shape
            np.testing.assert_array_equal(
                got.view(np.uint8), arr.view(np.uint8))

    def test_truncated_body_is_value_error(self):
        raw = encode_kv_payload(self._payload())
        with pytest.raises(ValueError, match="truncated"):
            decode_kv_payload(raw[:-10])

    def test_trailing_bytes_are_value_error(self):
        raw = encode_kv_payload(self._payload())
        with pytest.raises(ValueError, match="trailing"):
            decode_kv_payload(raw + b"x")

    def test_short_and_bogus_headers_are_value_errors(self):
        with pytest.raises(ValueError, match="too short"):
            decode_kv_payload(b"\x00\x00")
        with pytest.raises(ValueError, match="out of bounds"):
            decode_kv_payload(b"\xff\xff\xff\xff{}")
        with pytest.raises(ValueError, match="not JSON"):
            decode_kv_payload(b"\x00\x00\x00\x02xx")

    def test_wrong_version_rejected(self):
        p = self._payload()
        p["version"] = 99
        raw = encode_kv_payload(p)
        with pytest.raises(ValueError, match="version"):
            decode_kv_payload(raw)


class TestRemoteReplicaParity:
    """The Server-shaped duck type across the wire."""

    def test_remote_submit_byte_identical(self, fleet, ref24):
        pre, _ = fleet
        h = pre.submit(np.asarray(PROMPT, np.int32),
                       GenerationConfig(max_new_tokens=24))
        assert [int(t) for t in h.result(timeout=180)] == ref24

    def test_healthz_read_surface_is_cached(self, fleet):
        pre, _ = fleet
        snap = pre.load()
        for k in ("status", "queue_depth", "active_requests",
                  "free_slots", "free_pages", "max_len"):
            assert k in snap, k
        assert pre.status in ("ok", "draining")
        assert pre.queue.depth == snap["queue_depth"]
        assert pre.engine.max_len == snap["max_len"]
        assert pre.engine.alloc.free_pages >= 0
        assert "adapter-x" not in pre.engine.adapters

    def test_local_capacity_verdict_raises_value_error(self, fleet):
        pre, _ = fleet
        with pytest.raises(ValueError, match="max_len"):
            pre.submit(np.asarray(PROMPT, np.int32),
                       GenerationConfig(max_new_tokens=10_000))

    def test_streaming_cancel_shears_the_socket(self, fleet):
        pre, _ = fleet
        h = pre.submit(np.asarray(PROMPT, np.int32),
                       GenerationConfig(max_new_tokens=40))
        it = h.stream(timeout=120)
        next(it)
        h.cancel()
        deadline = time.monotonic() + 30
        while not h.done and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h.status == "cancelled"
        # the replica reclaims the slot (broken-pipe guard server-side)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if (pre.load().get("active_requests", 1) == 0
                    and pre.load().get("queue_depth", 1) == 0):
                break
            time.sleep(0.05)
        assert pre.load().get("active_requests") == 0


class TestDisaggregatedHandoff:
    """The acceptance scenario: prefill -> /kv/export -> /kv/import ->
    decode, byte-identical, idempotent, kill-tolerant."""

    def test_handoff_byte_identical_and_idempotent(self, fleet, ref24):
        pre, dec = fleet
        front = DisaggregatedFront(pre, dec)
        h = front.generate(np.asarray(PROMPT, np.int32),
                           GenerationConfig(max_new_tokens=24))
        got = [int(t) for t in h.result(timeout=180)]
        assert got == ref24
        assert front.handoffs >= 1          # pages actually shipped
        # idempotent re-ship: the chain hashes dedup every block
        out = front.ship(PROMPT)
        assert out["imported"] == 0
        assert out["deduped"] >= 1
        assert out["coverage"] == 16        # 2 full blocks of 8

    def test_export_frames_chain_hashes(self, fleet):
        pre, _ = fleet
        # self-sufficient: one budget-1 request registers the prompt's
        # blocks in the prefix index (prior tests may have evicted or
        # never parked them), then the export frames the chain
        h = pre.submit(np.asarray(PROMPT, np.int32),
                       GenerationConfig(max_new_tokens=1))
        h.result(timeout=180)
        raw = pre.export_kv_raw(PROMPT)
        payload = decode_kv_payload(raw)
        from paddle_tpu.inference.paged_cache import _chain_root

        assert payload["coverage"] == 16
        assert len(payload["blocks"]) == 2
        # the chain anchors at the salt's root digest, and each block
        # names its parent — what makes the import idempotent AND
        # corruption-evident (the importer recomputes every hash)
        assert (payload["blocks"][0]["parent"]
                == _chain_root(b"").hex())
        assert (payload["blocks"][1]["parent"]
                == payload["blocks"][0]["hash"])
        assert all(len(b["tokens"]) == 8 for b in payload["blocks"])

    def test_decode_kill_mid_stream_replays_on_prefill(
            self, fleet, ref24):
        pre, _ = fleet
        # a DEDICATED decode victim: killing the shared one would
        # starve the rest of the module
        proc, url = spawn_replica(REPLICA_ARGS, env=REPLICA_ENV)
        victim = RemoteReplica(url, proc=proc)
        assert victim.wait_ready(120)
        try:
            front = DisaggregatedFront(pre, victim)
            h = front.generate(np.asarray(PROMPT, np.int32),
                               GenerationConfig(max_new_tokens=24))
            it = h.stream(timeout=120)
            got = [int(next(it)) for _ in range(4)]
            proc.kill()                     # decode dies mid-stream
            got += [int(t) for t in it]
            assert got == ref24             # replayed on the prefill
            #                                 replica, byte-identical
            assert front.failovers >= 1
        finally:
            victim.shutdown(drain=False)


class TestRouterOverRemote:
    """Zero Router forks: RemoteReplicaSpec passes the isinstance
    gate, and breakers/failover/least-loaded run on the duck type."""

    def test_router_failover_on_process_kill(self, ref24):
        spec = RemoteReplicaSpec(args=REPLICA_ARGS)
        router = Router(spec, replicas=2, monitor_interval_s=0.1,
                        max_replica_restarts=0)
        try:
            assert router.wait_ready(120)
            assert router.status == "ok"
            h = router.submit(np.asarray(PROMPT, np.int32),
                              GenerationConfig(max_new_tokens=24))
            it = h.stream(timeout=120)
            got = [int(next(it)) for _ in range(2)]
            # kill the serving replica's PROCESS mid-stream
            router._replicas[h.replica].server.proc.kill()
            got += [int(t) for t in it]
            assert got == ref24
            assert h._failovers >= 1
            snap = router.load()
            assert snap["healthy"]          # the survivor still routes
        finally:
            router.shutdown(drain=False)


class TestWireFormatRollup:
    """Satellite: LatencyDigest/_ProgramRecord to_dict -> HTTP ->
    from_dict -> fleet_rollup across a REAL process boundary, with
    merge-exact (never averaged) fleet percentiles."""

    @staticmethod
    def _drive(rep, n, max_new):
        for _ in range(n):
            h = rep.submit(np.asarray(PROMPT, np.int32),
                           GenerationConfig(max_new_tokens=max_new))
            h.result(timeout=180)

    def test_latency_digest_round_trip_merge_exact(self, fleet):
        from paddle_tpu.monitor.slo import LatencyDigest, fleet_rollup

        pre, dec = fleet
        # different budgets -> different TPOT populations per replica
        self._drive(pre, 2, 8)
        self._drive(dec, 2, 16)
        s1 = pre.slo.digests_dict()
        s2 = dec.slo.digests_dict()
        for s in (s1, s2):
            assert "metrics" in s and "tpot" in s["metrics"], s.keys()
        out = fleet_rollup([s1, s2])
        # merge-exact: the fleet digest is the elementwise-summed
        # buckets, so its percentile equals the merged-digest
        # percentile EXACTLY — and its count is the plain sum
        tenant = next(iter(s1["metrics"]["tpot"]))
        d1 = LatencyDigest.from_dict(s1["metrics"]["tpot"][tenant])
        d2 = LatencyDigest.from_dict(s2["metrics"]["tpot"][tenant])
        merged = LatencyDigest.from_dict(
            s1["metrics"]["tpot"][tenant])
        merged.merge(d2)
        fleet_tpot = out["metrics"]["tpot"]["*"]
        assert fleet_tpot["count"] == d1.count + d2.count
        # summary() rounds for the JSON view; the underlying value is
        # the merged digest's percentile, bit-for-bit
        assert fleet_tpot["p99"] == round(merged.percentile(99), 6)
        # ... and NEVER the average of per-replica percentiles
        if d1.percentile(99) != d2.percentile(99):
            avg = round((d1.percentile(99) + d2.percentile(99)) / 2.0,
                        6)
            assert fleet_tpot["p99"] != avg

    def test_rolling_tpot_p50_over_the_wire(self, fleet):
        pre, _ = fleet
        # driven by the previous test; the skew detector's input works
        # through the same shard
        p50 = pre.slo.rolling_tpot_p50(min_count=1)
        assert p50 is None or p50 > 0

    def test_program_record_round_trip_and_merge(self, fleet):
        from paddle_tpu.monitor.ledger import (_ProgramRecord,
                                               merge_profiles)

        pre, dec = fleet
        s1, s2 = pre.profile(), dec.profile()
        assert s1["programs"], "child ledger must be enabled"
        pid, rec = next(iter(s1["programs"].items()))
        # to_dict -> (HTTP/JSON) -> from_dict -> to_dict is stable
        back = _ProgramRecord.from_dict(rec).to_dict()
        for k in ("program", "dispatches", "compiles", "flops"):
            assert back.get(k) == rec.get(k), k
        out = merge_profiles([s1, s2])
        common = set(s1["programs"]) & set(s2["programs"])
        assert common, "identical toy replicas share program ids"
        cid = next(iter(common))
        assert (out["programs"][cid]["dispatches"]
                == s1["programs"][cid]["dispatches"]
                + s2["programs"][cid]["dispatches"])


class TestStrictBodies:
    """Satellite: the silent-failure request-body class — unknown keys
    and type confusions are a 400 NAMING the offender, on the /kv
    endpoints and the non-bool ``stream`` field alike."""

    def test_kv_export_unknown_field_is_named_400(self, fleet):
        pre, _ = fleet
        status, body = _post(pre.base_url, "/kv/export",
                             {"tokens": PROMPT, "slat": ""})
        assert status == 400
        assert "slat" in body["error"]

    def test_kv_export_bad_tokens_is_400(self, fleet):
        pre, _ = fleet
        for bad in ([], ["a"], "nope", [True]):
            status, body = _post(pre.base_url, "/kv/export",
                                 {"tokens": bad})
            assert status == 400, bad
            assert "tokens" in body["error"]

    def test_kv_export_bad_salt_is_400(self, fleet):
        pre, _ = fleet
        status, body = _post(pre.base_url, "/kv/export",
                             {"tokens": PROMPT, "salt": 7})
        assert status == 400
        assert "salt" in body["error"]

    def test_kv_import_empty_and_garbage_bodies_are_400(self, fleet):
        pre, _ = fleet
        status, body = _post(pre.base_url, "/kv/import", b"",
                             ctype="application/octet-stream")
        assert status == 400
        status, body = _post(pre.base_url, "/kv/import", b"junk",
                             ctype="application/octet-stream")
        assert status == 400

    def test_kv_unknown_op_is_404(self, fleet):
        pre, _ = fleet
        status, _ = _post(pre.base_url, "/kv/exfiltrate", {})
        assert status == 404

    def test_generate_non_bool_stream_is_named_400(self, fleet):
        pre, _ = fleet
        status, body = _post(
            pre.base_url, "/generate",
            {"prompt": PROMPT, "max_new_tokens": 4,
             "stream": "false"})
        assert status == 400
        assert "stream" in body["error"]

    def test_generate_unknown_field_still_named_400(self, fleet):
        # the original typo'd-"adaptor" regression, across a real
        # process boundary
        pre, _ = fleet
        status, body = _post(
            pre.base_url, "/generate",
            {"prompt": PROMPT, "max_new_tokens": 4, "adaptor": "x"})
        assert status == 400
        assert "adaptor" in body["error"]

    def test_kv_endpoints_on_incapable_server_are_permanent_400(self):
        from paddle_tpu.serving import serve_http

        # prefix_cache OFF -> the capability gate answers 400 (not a
        # retryable 503): this front can never serve a handoff
        srv = Server(make_engine(prefix_cache=False), segment_steps=2)
        httpd = serve_http(srv, port=0)
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            status, body = _post(url, "/kv/export",
                                 {"tokens": PROMPT})
            assert status == 400
            assert "prefix_cache" in body["error"]
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)
