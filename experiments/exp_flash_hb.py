"""Prototype: head-batched flash fwd — all q heads per block share the
K/V block (KV HBM traffic /H, grid /H). Standalone experiment before
integrating. Run: python experiments/exp_flash_hb.py
"""
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _fwd_hb_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                   sm_scale, causal, block_q, block_k, h):
    b, iq, ik = (pl.program_id(i) for i in range(3))
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]                        # (H, bq, D)
        k = k_ref[0]                        # (H, bk, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale  # (H, bq, bk)
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = (kpos <= qpos)[None]
            s = jnp.where(valid, s, NEG)
        m_prev = m_ref[...]                 # (H, bq)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = (acc_ref[...] * alpha[..., None]
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v,
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32))
        m_ref[...] = m_new
        l_ref[...] = l_new

    if causal:
        pl.when(ik * block_k <= iq * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _fin():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[..., None]).astype(o_ref.dtype)


def flash_fwd_hb(q, k, v, causal=True, block_q=512, block_k=512):
    bsz, h, s, d = q.shape
    bq, bk = min(block_q, s), min(block_k, s)
    nq, nk = s // bq, s // bk
    return pl.pallas_call(
        functools.partial(_fwd_hb_kernel, sm_scale=1.0 / np.sqrt(d),
                          causal=causal, block_q=bq, block_k=bk, h=h),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bsz, nq, nk),
        in_specs=[
            pl.BlockSpec((1, h, bq, d), lambda b, i, j: (b, 0, i, 0)),
            pl.BlockSpec((1, h, bk, d), lambda b, i, j: (b, 0, j, 0)),
            pl.BlockSpec((1, h, bk, d), lambda b, i, j: (b, 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, bq, d), lambda b, i, j: (b, 0, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, bq, d), jnp.float32),
            pltpu.VMEM((h, bq), jnp.float32),
            pltpu.VMEM((h, bq), jnp.float32),
        ],
    )(q, k, v)


def main():
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from exp_micro import timed
    from paddle_tpu.ops.flash_attention_kernel import flash_attention_bhsd

    B, H, S, D = 8, 8, 2048, 128
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), jnp.bfloat16)

    # numerical parity vs the production kernel
    o_ref = flash_attention_bhsd(q[:1, :, :1024], k[:1, :, :1024],
                                 v[:1, :, :1024], causal=True)
    o_hb = flash_fwd_hb(q[:1, :, :1024], k[:1, :, :1024], v[:1, :, :1024],
                        causal=True, block_q=256, block_k=256)
    err = float(jnp.max(jnp.abs(o_hb.astype(jnp.float32)
                                - o_ref.astype(jnp.float32))))
    print("parity maxerr:", err, flush=True)
    assert err < 2e-2

    att = 2 * B * H * S * S * D
    for bq, bk in [(256, 256), (256, 512), (512, 256), (128, 512),
                   (512, 512), (128, 1024), (256, 1024)]:
        try:
            t = timed(lambda q, k, v: flash_fwd_hb(q, k, v, causal=True,
                                                   block_q=bq, block_k=bk),
                      (q, k, v), iters=10)
            print(json.dumps({"bq": bq, "bk": bk,
                              "hb_fwd_ms": round(t * 1e3, 3),
                              "mxu_pct": round(100 * att / t / 394e12, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"bq": bq, "bk": bk,
                              "error": str(e)[:120]}), flush=True)


if __name__ == "__main__":
    main()
