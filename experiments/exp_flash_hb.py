"""Head-batched (BSHD-native) vs per-head (BHSD) flash kernel on TPU.

Measures, at the 350M bench shapes, the END-TO-END cost each path implies:
kernel fwd / fwd+bwd PLUS the BSHD<->BHSD transposes the per-head path
forces on the caller. Decides FLAGS_flash_head_batched.
(The round-2 fwd-only prototype this file held is superseded by the real
fwd+bwd kernel in paddle_tpu/ops/flash_attention_hb.py.)

Run: python experiments/exp_flash_hb.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from exp_micro import timed
    from paddle_tpu.ops.flash_attention_hb import flash_attention_bshd_hb
    from paddle_tpu.ops.flash_attention_kernel import flash_attention_bhsd

    B, S, H, D = 8, 2048, 8, 128
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, H, D), jnp.bfloat16)

    def per_head(q, k, v):
        # what ops/pallas.flash_attention does today: transpose around
        # the BHSD kernel — the transposes are PART of this path's cost
        qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
        out = flash_attention_bhsd(qt, kt, vt, causal=True)
        return jnp.swapaxes(out, 1, 2)

    variants = {"per_head_1024": per_head}
    for blk in (256, 512):
        variants[f"hb_{blk}"] = (
            lambda q, k, v, b=blk: flash_attention_bshd_hb(
                q, k, v, causal=True, block_q=b, block_k=b))

    results = {}
    for name, f in variants.items():
        try:
            fwd_ms = timed(jax.jit(f), (q, k, v)) * 1e3

            def loss(q, k, v, _f=f):
                return jnp.sum(_f(q, k, v).astype(jnp.float32))

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            bwd_ms = timed(g, (q, k, v)) * 1e3
            results[name] = {"fwd_ms": round(fwd_ms, 3),
                             "fwdbwd_ms": round(bwd_ms, 3)}
        except Exception as e:  # noqa: BLE001 - report per-variant
            results[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
        print(json.dumps({name: results[name]}), flush=True)

    timed_rs = [(r["fwdbwd_ms"], n) for n, r in results.items()
                if "fwdbwd_ms" in r]
    if timed_rs:
        best = min(timed_rs)
        print(json.dumps({"best": best[1], "fwdbwd_ms": best[0],
                          "flip_flag": best[1].startswith("hb_")}))


if __name__ == "__main__":
    main()
