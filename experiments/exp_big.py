"""MFU at larger configs: does wider hidden lift MXU utilization enough
to beat the 350m number? Run: python experiments/exp_big.py [name ...]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

CONFIGS = {
    # name: (preset, overrides, batch, seq)
    "770m": ("350m", dict(hidden_size=1536, intermediate_size=4096,
                          num_attention_heads=12, num_key_value_heads=12),
             8, 2048),
    "770m_b4": ("350m", dict(hidden_size=1536, intermediate_size=4096,
                             num_attention_heads=12,
                             num_key_value_heads=12), 4, 2048),
    "1b3": ("1b3", dict(num_attention_heads=16, num_key_value_heads=16),
            4, 2048),
}


def run(name):
    import jax
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.models.llama_functional import (build_train_step,
                                                    stack_params)

    preset, over, B, S = CONFIGS[name]
    cfg = llama_config(preset, dtype="bfloat16",
                       max_position_embeddings=S, recompute="full", **over)
    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    stacked, rest = stack_params(params, cfg)
    step, init = build_train_step(cfg, lr=1e-4, remat=True)
    st = init(stacked, rest)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    lab = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    jitted = jax.jit(step, donate_argnums=(0, 1, 2))
    stacked, rest, st, loss = jitted(stacked, rest, st, ids, lab)
    _ = float(loss)
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        stacked, rest, st, loss = jitted(stacked, rest, st, ids, lab)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / iters
    toks = B * S
    mfu = 6.0 * n_params * toks / dt / 394e12
    print(json.dumps({"exp": name, "params": n_params,
                      "tps": round(toks / dt, 1),
                      "mfu": round(mfu, 4),
                      "ms_per_step": round(dt * 1e3, 1)}), flush=True)


if __name__ == "__main__":
    names = sys.argv[1:] or list(CONFIGS)
    for n in names:
        try:
            run(n)
        except Exception as e:
            print(json.dumps({"exp": n, "error": str(e)[:200]}), flush=True)
