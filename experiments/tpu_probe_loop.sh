#!/bin/bash
# Round-5 continuous tunnel probe (VERDICT r4 Next #1): probe the axon
# TPU claim every PROBE_INTERVAL seconds from a SUBPROCESS with a hard
# timeout (a wedged claim hangs jax.devices() forever — never probe
# in-process), and the moment a window opens, run the prepared session
# runbook end-to-end.  One claim at a time: the probe process exits
# before the runbook starts.
set -u
cd "$(dirname "$0")/.."
LOG=TPU_PROBES_r5.log
N=${PROBE_START:-1}
while true; do
  ts=$(date -u +%FT%TZ)
  # -k 10: the probe itself can ignore TERM while stuck in
  # make_c_api_client; KILL follows.  270s absorbs the ~2.3s
  # sitecustomize import plus slow-but-live tunnel handshakes.
  out=$(timeout -k 10 270 python -c \
    "import jax; ds=jax.devices(); print('PLAT', ds[0].platform, len(ds))" \
    2>&1)
  rc=$?
  if [ "$rc" -eq 0 ] && printf '%s' "$out" | grep -q "PLAT tpu"; then
    echo "$ts probe$N: WINDOW OPEN ($out) -> runbook" >>"$LOG"
    touch experiments/TPU_WINDOW_OPEN
    bash experiments/tpu_session.sh
    echo "$(date -u +%FT%TZ) probe$N: runbook finished" >>"$LOG"
    rm -f experiments/TPU_WINDOW_OPEN
  elif [ "$rc" -eq 0 ]; then
    echo "$ts probe$N: devices up but not tpu ($out)" >>"$LOG"
  else
    echo "$ts probe$N: no devices (claim hung or timeout, rc=$rc)" >>"$LOG"
  fi
  N=$((N + 1))
  sleep "${PROBE_INTERVAL:-600}"
done
