"""On-TPU autotune sweep (VERDICT r3 #8): block sizes for flash fwd+bwd
and decode_mha at the llama bench/serving shapes, persisted to the
IN-REPO cache (.autotune_cache.json) so `bench.py` picks tuned blocks on
first run. Commit the file after a successful sweep.

Run: python experiments/exp_autotune_sweep.py        (TPU; ~3-5 min)

Each tune target runs in its OWN subprocess with a wall-clock budget
(EXP_TRIAL_SECS, default 900) and saves its winner into the repo cache
INCREMENTALLY (AutoTuneCache.load merges) — the 2026-07-31 session hung
in the first trial's remote compile and produced nothing; with per-trial
isolation a wedged compile costs one entry, not the sweep.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# flash at the two bench configs (350M: h8 d128 s2048; 1.3B: h16 d128).
# grad=True ONLY: the cache key has no fwd/bwd distinction (the router
# consults one key for both), so the tuned config must optimize the
# TRAINING (fwd+bwd) path — a later fwd-only tune would clobber it.
TARGETS = [
    {"kind": "flash", "b": 8, "h": 8, "s": 2048, "d": 128},
    {"kind": "flash", "b": 4, "h": 16, "s": 2048, "d": 128},
    {"kind": "flash", "b": 8, "h": 8, "s": 1024, "d": 128},
    # decode at serving shapes (engine max_len 2048/4096)
    {"kind": "decode", "b": 8, "h": 8, "s_max": 2048, "d": 128},
    {"kind": "decode", "b": 8, "h": 8, "s_max": 4096, "d": 128},
]


def tune_one(spec: dict):
    import jax

    if os.environ.get("EXP_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    cache = os.path.join(REPO, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from paddle_tpu.ops import autotune

    # FRESH table, then merge ONLY the repo file: a per-user cache
    # (CPU/interpret entries from prior tune() auto-saves) must never
    # leak into the committed real-hardware file; merging the repo file
    # first makes each trial's save incremental instead of clobbering
    repo_cache = os.path.join(REPO, ".autotune_cache.json")
    autotune._GLOBAL = autotune.AutoTuneCache()
    autotune._loaded[0] = True
    try:
        autotune._GLOBAL.load(repo_cache)
    except (OSError, ValueError) as e:  # corrupt file loses one merge,
        print(json.dumps({"warning":     # not the whole sweep
                          f"repo cache unreadable ({e}); starting fresh"}),
              flush=True)
    autotune.set_cache_path(repo_cache)
    if jax.default_backend() != "tpu":
        print(json.dumps({"warning": "not on TPU — sweep would record "
                          "meaningless CPU timings; refusing to persist"}))
        return
    if spec["kind"] == "flash":
        cfg = autotune.tune_flash(spec["b"], spec["h"], spec["s"],
                                  spec["d"], causal=True,
                                  dtype="bfloat16", grad=True)
        label = f"flash s={spec['s']} h={spec['h']} fwd+bwd"
    else:
        cfg = autotune.tune_decode_mha(spec["b"], spec["h"],
                                       spec["s_max"], spec["d"],
                                       dtype="bfloat16")
        label = f"decode s_max={spec['s_max']}"
    autotune.get_cache().save()
    print(json.dumps({label: cfg, "saved": True}), flush=True)


def main():
    from _budget import run_budgeted

    budget = int(os.environ.get("EXP_TRIAL_SECS", "900"))
    saved = 0
    for spec in TARGETS:
        r = run_budgeted([sys.executable, "-u", os.path.abspath(__file__),
                          "--one", json.dumps(spec)], budget)
        if r.timed_out:
            print(json.dumps({str(spec): f"hung >{budget}s "
                              "(group killed)"}), flush=True)
        if r.err.strip():
            sys.stderr.write(f"--- {spec} stderr tail ---\n"
                             + r.err[-2000:] + "\n")
        for ln in r.out.splitlines():
            if ln.strip().startswith("{"):
                print(ln, flush=True)
                if '"saved": true' in ln:
                    saved += 1
    path = os.path.join(REPO, ".autotune_cache.json")
    entries = 0
    if os.path.exists(path):
        try:
            with open(path) as f:
                entries = len(json.load(f))
        except ValueError:
            entries = "unreadable"
    print(json.dumps({"cache_file": path, "entries": entries,
                      "trials_saved": saved, "of": len(TARGETS)}))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        tune_one(json.loads(sys.argv[2]))
    else:
        main()
