"""On-TPU autotune sweep (VERDICT r3 #8): block sizes for flash fwd+bwd
and decode_mha at the llama bench/serving shapes, persisted to the
IN-REPO cache (.autotune_cache.json) so `bench.py` picks tuned blocks on
first run. Commit the file after a successful sweep.

Run: python experiments/exp_autotune_sweep.py        (TPU; ~3-5 min)
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax

    if os.environ.get("EXP_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    cache = os.path.join(REPO, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from paddle_tpu.ops import autotune

    # FRESH table: a merged per-user cache (CPU/interpret entries from
    # prior tune() auto-saves) must never leak into the committed
    # real-hardware file
    autotune._GLOBAL = autotune.AutoTuneCache()
    autotune._loaded[0] = True
    autotune.set_cache_path(os.path.join(REPO, ".autotune_cache.json"))
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        print(json.dumps({"warning": "not on TPU — sweep would record "
                          "meaningless CPU timings; refusing to persist"}))
        return

    results = {}
    # flash at the two bench configs (350M: h8 d128 s2048; 1.3B: h16 d128).
    # grad=True ONLY: the cache key has no fwd/bwd distinction (the router
    # consults one key for both), so the tuned config must optimize the
    # TRAINING (fwd+bwd) path — a later fwd-only tune would clobber it.
    for b, h, s, d in ((8, 8, 2048, 128), (4, 16, 2048, 128),
                       (8, 8, 1024, 128)):
        cfg = autotune.tune_flash(b, h, s, d, causal=True,
                                  dtype="bfloat16", grad=True)
        results[f"flash_b{b}h{h}s{s}_grad"] = cfg
        print(json.dumps({f"flash s={s} h={h} fwd+bwd": cfg}), flush=True)
    # decode at serving shapes (engine max_len 2048/4096)
    for b, h, s_max, d in ((8, 8, 2048, 128), (8, 8, 4096, 128)):
        cfg = autotune.tune_decode_mha(b, h, s_max, d, dtype="bfloat16")
        results[f"decode_s{s_max}"] = cfg
        print(json.dumps({f"decode s_max={s_max}": cfg}), flush=True)

    autotune.get_cache().save()
    print(json.dumps({"saved": os.path.join(REPO, ".autotune_cache.json"),
                      "entries": autotune.get_cache().stats}))


if __name__ == "__main__":
    main()
