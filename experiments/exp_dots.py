"""The dots-bucket attack plan (PERF.md Headroom #1): dots sit at ~43%
MXU and dominate the 350M step (223ms). Each experiment here is an
UNTRIED lever (merged-QKV and remat="dots" already measured and
rejected — see PERF.md "did NOT work"); run on TPU, flip a default only
on a >=3% full-step win.

  E1 scan unroll      — lax.scan(unroll=k) exposes k consecutive layers
                        to one XLA fusion scope: boundary relayouts and
                        convert tails can fuse across layers. Measures
                        the FULL 350M loss fwd+bwd at unroll 1/2/4.
  E2 dot form         — [B,S,H]x[H,N] einsum vs reshape-to-2D
                        [B*S,H]@[H,N]: batched-3D vs flat-2D tiling.
  E3 rhs layout       — W[in,out] (ours) vs W[out,in] consumed as
                        dot_general with contracting dim 1 ("transposed
                        weights"): whether XLA inserts a relayout for
                        one of the forms at bf16.
  E4 dot out dtype    — bf16 dot -> f32 output (preferred_element_type)
                        vs bf16 output + later upcast: convert-tail
                        fusion (PERF.md's ~25ms convert bucket).
  E5 remat attn_out   — jax.checkpoint save_only_these_names("attn_out"):
                        keep ONLY flash outputs across the scan; kills
                        the refwd-flash bucket (~22ms/step) for ~800MB
                        (vs remat="dots"'s rejected 8.4GB).

Run: python experiments/exp_dots.py            (TPU; ~2 min)

Each variant runs in its OWN subprocess with a wall-clock budget
(EXP_VARIANT_SECS, default 600): the 2026-07-31 session lost the whole
experiment when the FIRST variant's remote compile died on a transport
error and the process then hung to the step timeout — per-variant
isolation means one wedged compile costs one variant, not the session.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VARIANTS = ("E1_unroll1", "E1_unroll2", "E1_unroll4", "E5_remat_attn_out",
            "E2_einsum3d", "E2_flat2d", "E3_rhs_transposed", "E4_f32_out")


def run_variants():
    """Parent: one subprocess per variant via the shared budget harness
    (own session, TERM-then-KILL group, SIGTERM forwarded — a hung
    remote-compile helper can never outlive us holding the claim)."""
    from _budget import run_budgeted

    budget = int(os.environ.get("EXP_VARIANT_SECS", "600"))
    lines = []
    for name in VARIANTS:
        r = run_budgeted([sys.executable, "-u", os.path.abspath(__file__),
                          "--variant", name], budget)
        if r.timed_out:
            print(json.dumps({name: f"hung >{budget}s (group killed)"}),
                  flush=True)
        if r.err.strip():
            sys.stderr.write(f"--- {name} stderr tail ---\n"
                             + r.err[-2000:] + "\n")
        got = [ln for ln in r.out.splitlines()
               if ln.strip().startswith("{")]
        for ln in got:
            print(ln, flush=True)
        if got:
            lines.append(name)
    print(json.dumps({"variants_with_output": len(lines),
                      "of": len(VARIANTS)}))


def main(only: str = None):
    import jax

    if os.environ.get("EXP_FORCE_CPU"):
        # the axon sitecustomize force-sets jax_platforms; the env var
        # alone cannot pin CPU (see tests/conftest.py note)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from exp_micro import timed

    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.models.llama_functional import (build_loss_fn,
                                                    stack_params)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = llama_config("350m", dtype="bfloat16", num_attention_heads=8,
                           num_key_value_heads=8,
                           max_position_embeddings=2048, recompute="full")
        B, S = 8, 2048
    else:
        cfg = llama_config("tiny")
        B, S = 2, 64
    rng = np.random.RandomState(0)
    results = {}
    full_step = [(f"E1_unroll{u}", dict(remat=True, scan_unroll=u))
                 for u in (1, 2, 4)]
    # E5: selective remat — save ONLY the flash outputs; kills the
    # refwd-flash bucket (~22ms/step) for ~800MB at bench shapes (vs the
    # rejected remat="dots" 8.4GB)
    full_step.append(("E5_remat_attn_out", dict(remat="attn_out")))
    full_step = [fs for fs in full_step
                 if only is None or only == fs[0]]
    if full_step:
        model = LlamaForCausalLM(cfg)
        params = {k: p.value for k, p in model.named_parameters()}
        stacked, rest = stack_params(params, cfg)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
        y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    # ---- E1/E5: full loss fwd+bwd (scan unroll / remat policy) -------------
    for vname, build_kw in full_step:
        try:
            loss_fn = build_loss_fn(cfg, **build_kw)

            # timed() chains its perturbation through arg 0, which must
            # be a float array: thread the embedding weight through
            def gfn(emb_w, _lf=loss_fn):
                r2 = dict(rest)
                r2["model.embed_tokens.weight"] = emb_w
                return jax.grad(
                    lambda p: _lf(p["s"], p["r"], ids, y))(
                        {"s": stacked, "r": r2})

            ms = timed(jax.jit(gfn),
                       (rest["model.embed_tokens.weight"],)) * 1e3
            results[f"{vname}_fwdbwd_ms"] = round(ms, 2)
        except Exception as e:  # noqa: BLE001
            results[f"{vname}_fwdbwd_ms"] = \
                f"{type(e).__name__}: {e}"[:120]
        print(json.dumps({vname: results[f"{vname}_fwdbwd_ms"]}),
              flush=True)

    # ---- E2/E3/E4: dot micro-forms at layer shapes -------------------------
    H, I = cfg.hidden_size, cfg.intermediate_size
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    x3 = jnp.asarray(rng.randn(B, S, H), dt)
    w = jnp.asarray(rng.randn(H, I) * 0.02, dt)
    wt = jnp.asarray(np.asarray(w).T.copy())

    def e2_einsum(x, w):
        return jnp.einsum("bsh,hi->bsi", x, w)

    def e2_flat(x, w):
        return (x.reshape(-1, H) @ w).reshape(B, S, I)

    def e3_transposed(x, wt):
        return jax.lax.dot_general(x, wt, (((2,), (1,)), ((), ())))

    def e4_f32out(x, w):
        return jax.lax.dot_general(
            x, w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dt)

    for name, fn, args in (
            ("E2_einsum3d", e2_einsum, (x3, w)),
            ("E2_flat2d", e2_flat, (x3, w)),
            ("E3_rhs_transposed", e3_transposed, (x3, wt)),
            ("E4_f32_out", e4_f32out, (x3, w))):
        if only is not None and only != name:
            continue
        try:
            ms = timed(jax.jit(fn), args) * 1e3
            results[f"{name}_ms"] = round(ms, 3)
        except Exception as e:  # noqa: BLE001
            results[f"{name}_ms"] = f"{type(e).__name__}: {e}"[:120]
        print(json.dumps({name: results[f"{name}_ms"]}), flush=True)

    print(json.dumps({"platform": jax.default_backend(), **results}))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--variant":
        main(only=sys.argv[2])
    else:
        run_variants()
