"""Step-level time decomposition for the bench config on TPU.

Times: full train step / grad-only / loss fwd / logits fwd, all chained
(params perturbed by tiny*result each iteration) with scalar readback.
Run: python experiments/exp_step.py [iters]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.models.llama_functional import (build_loss_fn,
                                                    build_train_step, forward,
                                                    stack_params)

    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    cfg = llama_config("350m", dtype="bfloat16",
                       num_attention_heads=8, num_key_value_heads=8,
                       max_position_embeddings=2048, recompute="full")
    B, S = 8, 2048
    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    stacked, rest = stack_params(params, cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)

    def bench(name, make_loop, flops_per_tok=None):
        jit = jax.jit(make_loop, static_argnums=(1,))
        _ = float(jit((stacked, rest), iters))
        t0 = time.perf_counter()
        _ = float(jit((stacked, rest), iters))
        dt = (time.perf_counter() - t0) / iters
        rec = {"ms_per_iter": round(dt * 1e3, 2)}
        if flops_per_tok:
            rec["mfu"] = round(flops_per_tok * B * S / dt / 394e12, 4)
        print(json.dumps({name: rec}), flush=True)

    loss_fn = build_loss_fn(cfg, remat="full")
    loss_fn_dots = build_loss_fn(cfg, remat="dots")

    def perturb(p, scalar):
        eps = scalar.astype(jnp.float32) * 1e-30
        return jax.tree.map(lambda a: a + eps.astype(a.dtype), p)

    def loop_fwd_logits(p, n):
        def body(_, p):
            lg = forward(p[0], p[1], ids, cfg, remat="full")
            return (perturb(p[0], jnp.sum(lg[..., :64].astype(jnp.float32))),
                    p[1])
        p = jax.lax.fori_loop(0, n, body, p)
        return jnp.sum(p[0]["input_layernorm.weight"].astype(jnp.float32))

    def loop_fwd_loss(p, n):
        def body(_, p):
            l = loss_fn(p[0], p[1], ids, labels)
            return (perturb(p[0], l), p[1])
        p = jax.lax.fori_loop(0, n, body, p)
        return jnp.sum(p[0]["input_layernorm.weight"].astype(jnp.float32))

    def loop_grad(p, n):
        def body(_, p):
            l, g = jax.value_and_grad(
                lambda q: loss_fn(q["s"], q["r"], ids, labels))(
                    {"s": p[0], "r": p[1]})
            return (perturb(p[0], l + jnp.sum(
                g["s"]["input_layernorm.weight"].astype(jnp.float32))), p[1])
        p = jax.lax.fori_loop(0, n, body, p)
        return jnp.sum(p[0]["input_layernorm.weight"].astype(jnp.float32))

    def loop_grad_dots(p, n):
        def body(_, p):
            l, g = jax.value_and_grad(
                lambda q: loss_fn_dots(q["s"], q["r"], ids, labels))(
                    {"s": p[0], "r": p[1]})
            return (perturb(p[0], l + jnp.sum(
                g["s"]["input_layernorm.weight"].astype(jnp.float32))), p[1])
        p = jax.lax.fori_loop(0, n, body, p)
        return jnp.sum(p[0]["input_layernorm.weight"].astype(jnp.float32))

    from paddle_tpu.optimizer.functional import (adamw_init, adamw_update,
                                                 clip_by_global_norm)

    opt0 = adamw_init({"s": stacked, "r": rest})

    def loop_opt_only(p, n):
        grads = jax.tree.map(jnp.ones_like, {"s": p[0], "r": p[1]})

        def body(_, carry):
            pv, st = carry
            g, _ = clip_by_global_norm(grads, 1.0)
            st, pv = adamw_update(g, st, pv, lr=1e-4)
            return pv, st

        pv, st = jax.lax.fori_loop(0, n, body, ({"s": p[0], "r": p[1]}, opt0))
        return jnp.sum(pv["s"]["input_layernorm.weight"].astype(jnp.float32))

    def loop_clip_only(p, n):
        def body(_, carry):
            _, nrm = clip_by_global_norm(carry, 1.0)
            return jax.tree.map(
                lambda a: a + (nrm * 1e-30).astype(a.dtype), carry)

        out = jax.lax.fori_loop(0, n, body, {"s": p[0], "r": p[1]})
        return jnp.sum(out["s"]["input_layernorm.weight"].astype(jnp.float32))

    bench("fwd_logits", loop_fwd_logits, 2 * n_params)
    bench("fwd_loss", loop_fwd_loss, 2 * n_params)
    bench("grad_full_remat", loop_grad, 6 * n_params)
    bench("grad_dots_remat", loop_grad_dots, 6 * n_params)
    bench("opt_clip_update", loop_opt_only)
    bench("clip_only", loop_clip_only)


if __name__ == "__main__":
    main()
