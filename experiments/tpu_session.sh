#!/bin/bash
# First-TPU-session runbook (VERDICT r3 #1/#8, PERF.md attack plan) —
# run the moment the tunnel is up. Order matters:
#   1. flash parity ON-CHIP (the diagonal-block specialization is
#      default-on but has only ever run in interpret mode — Weak #2)
#   2. the round-record bench
#   3. kernel/layout experiments that decide flags
#   4. autotune sweep persisted in-repo
#   5. the bigger configs
# Every step appends to experiments/tpu_session.log; steps are
# independent — a failure moves on (the log is the evidence either way).
set -u
cd "$(dirname "$0")/.."
LOG=experiments/tpu_session.log
run() {
  echo "=== $(date -u +%FT%TZ) $*" | tee -a "$LOG"
  timeout "${STEP_TIMEOUT:-2400}" "$@" 2>&1 | tee -a "$LOG"
  local rc=${PIPESTATUS[0]}   # the COMMAND's status, not tee's
  echo "=== rc=$rc ===" | tee -a "$LOG"
}

# 1. kernel parity on real hardware (conftest escape hatch)
run env PADDLE_TPU_TESTS_ON_DEVICE=1 python -m pytest \
    tests/test_flash_attention.py tests/test_flash_hb.py \
    tests/test_pallas_kernels.py -q -p no:cacheprovider
# 2. round record
run python bench.py
# 3. flag-deciding experiments
run python experiments/exp_flash_hb.py     # FLAGS_flash_head_batched
run python experiments/exp_dots.py         # scan_unroll default
# 4. autotune sweep -> .autotune_cache.json (commit it)
run python experiments/exp_autotune_sweep.py
# 5. bigger configs
run python bench.py 1.3b
run python bench.py ragged
run python bench.py decode
echo "=== session done; review $LOG, flip flags per PERF.md decision" \
     "rules, re-run bench.py, commit .autotune_cache.json ===" | tee -a "$LOG"
