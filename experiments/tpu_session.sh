#!/bin/bash
# First-TPU-session runbook (VERDICT r3 #1/#8, PERF.md attack plan) —
# run the moment the tunnel is up. Order matters:
#   1. flash parity ON-CHIP (the diagonal-block specialization is
#      default-on but has only ever run in interpret mode — Weak #2)
#   2. the round-record bench
#   3. kernel/layout experiments that decide flags
#   4. autotune sweep persisted in-repo
#   5. the bigger configs
# Every step appends to experiments/tpu_session.log; steps are
# independent — a failure moves on (the log is the evidence either way).
set -u
cd "$(dirname "$0")/.."
LOG=experiments/tpu_session.log
run() {
  # Each step runs in its OWN process group (setsid) and the whole group
  # is SIGKILLed on timeout — `timeout` alone signals only the direct
  # child, and a remote-compile helper orphaned that way keeps holding
  # the device claim for every later step (observed 2026-07-31: exp_dots
  # and the autotune sweep hung >20min; killing the script leaked the
  # sweep process, which then wedged the claim for fresh probes).
  echo "=== $(date -u +%FT%TZ) $* (output -> $LOG; tail -f it)" \
    | tee -a "$LOG"
  setsid "$@" >>"$LOG" 2>&1 &
  local pid=$! t=${STEP_TIMEOUT:-2400} waited=0 rc
  while kill -0 "$pid" 2>/dev/null && [ "$waited" -lt "$t" ]; do
    sleep 5; waited=$((waited + 5))
  done
  if kill -0 "$pid" 2>/dev/null; then
    # TERM first: the bench/experiment watchdogs trap it to reap their
    # own detached children (which live in their OWN sessions and would
    # escape a bare group-KILL); KILL after a grace period
    kill -TERM -- "-$pid" 2>/dev/null
    local grace=0
    while kill -0 "$pid" 2>/dev/null && [ "$grace" -lt 15 ]; do
      sleep 1; grace=$((grace + 1))
    done
    kill -KILL -- "-$pid" 2>/dev/null
    rc=137
  else
    wait "$pid"; rc=$?
  fi
  echo "=== rc=$rc ===" | tee -a "$LOG"
}

# 0. PREFLIGHT: the invariant linter (~3s, CPU-only — no device claim).
#    A TPU window must never burn minutes on a program that would
#    recompile per request (PT001) or block its scheduler gap on host
#    syncs (PT002): fail the serving-invariant gate HERE, before any
#    chip time is spent. Like every step it logs-and-continues, but an
#    unbaselined finding in the log taints the window's serving records.
STEP_TIMEOUT=300 run python -m tools.lint paddle_tpu/ --summary

# 1. QUICK kernel parity slice on real hardware (conftest escape
#    hatch): the bench-path shapes (device_scale, d=64/128) plus the r5
#    sub-lane modes (pad/kpad/fp32 — kpad's in-kernel concat is the one
#    Mosaic-unverified lowering). TIGHT timeout: a 35-min window must
#    reach the record bench even if cold remote compiles are slow; the
#    FULL parity suite runs later (step 6b).
STEP_TIMEOUT=900 run env PADDLE_TPU_TESTS_ON_DEVICE=1 \
    python -m pytest tests/test_flash_attention.py \
    -k "device_scale or Sublane" -q -p no:cacheprovider
# 2. round record (bench has its own group-killing watchdog: accelerator
#    attempt BENCH_WATCHDOG_SECS then a 600s CPU retry — keep the outer
#    step timeout above their sum so the CPU retry can finish)
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py
# ---- steps 3+ ordered by VALUE-PER-MINUTE: the 2026-07-31 window
# ---- lasted 35 min and died before any lever was measured — the
# ---- MFU-moving experiments go before the bigger-config benches
# 3. flag-deciding experiments (cheap compiles, decide defaults)
run python experiments/exp_flash_hb.py     # FLAGS_flash_head_batched
# exp_dots: 8 variants x EXP_VARIANT_SECS(600) worst case — the step
# timeout must cover the per-variant budgets, not fight them
STEP_TIMEOUT=5100 run python experiments/exp_dots.py   # scan_unroll+remat
# 4. lever A/B on the full bench (log evidence, not the round record;
#    flip a default in code only on a >=3% full-step win per PERF.md)
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 BENCH_REMAT=attn_out \
    python bench.py
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 BENCH_SCAN_UNROLL=2 \
    python bench.py
# 5. autotune sweep -> .autotune_cache.json (commit it); 5 trials x
#    EXP_TRIAL_SECS(900)
STEP_TIMEOUT=4800 run python experiments/exp_autotune_sweep.py
# 6. bigger configs (cold-cache compiles can be slow through the tunnel)
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py 1.3b
# 6b. FULL kernel parity on-chip (the quick slice in step 1 covered the
#     bench path; this covers everything else incl. the head-batched
#     kernel, whose device routing stays off until green + measured win)
run env PADDLE_TPU_TESTS_ON_DEVICE=1 PADDLE_TPU_HB_ON_DEVICE=1 \
    python -m pytest \
    tests/test_flash_attention.py tests/test_flash_hb.py \
    tests/test_pallas_kernels.py tests/test_paged_attention.py \
    -q -p no:cacheprovider
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py ragged
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py decode
# speculative decode: tokens/forward + WALL speedup (decode is HBM-bound
# on TPU, so unlike the CPU fallback the wall number should track the
# tokens/forward ratio)
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py spec
# 6c. FIRST on-chip online-serving records (every serve_bench number so
#     far is CPU-tiny): the prefix-caching A/B is the highest-value
#     serving pair — TTFT p50/p99 + serve_kv_occupancy +
#     serve_prefix_hit_rate, cold then warm (PERF.md "Automatic prefix
#     caching" methodology; 11.2x TTFT p50 on CPU tiny — the on-chip
#     ratio decides whether the cache defaults on for serving configs)
STEP_TIMEOUT=2400 run python tools/serve_bench.py --shared-prefix-len 448 \
    --cache-prefixes off --num-pages 320 --max-pages 64 --page-size 8 \
    --requests 16 --rate 4 --max-new 8 --segment-steps 2 \
    --prompt-len 4:8 --layers 2 --prefill-chunk 64 --warmup
STEP_TIMEOUT=2400 run python tools/serve_bench.py --shared-prefix-len 448 \
    --cache-prefixes on --num-pages 320 --max-pages 64 --page-size 8 \
    --requests 16 --rate 4 --max-new 8 --segment-steps 2 \
    --prompt-len 4:8 --layers 2 --prefill-chunk 64 --warmup
# 6d. on-TPU SPECULATIVE SERVING A/B (first hardware numbers for the
#     batched spec path — every spec-serving number so far is CPU-tiny
#     and CPU is compute-bound, so its wall ratio is honestly <1x;
#     decode on TPU is HBM-bound, so serve_spec_tokens_per_forward
#     should convert into the TPOT ratio here. One invocation runs
#     both arms on identical load; read serve_tpot_p50_plain/_spec,
#     serve_spec_tokens_per_forward, serve_spec_acceptance_rate)
STEP_TIMEOUT=2400 run python tools/serve_bench.py --spec-ab --draft-k 6 \
    --repeat-unit 4 --layers 2 --prompt-len 28:32 --max-new 32 \
    --rate 8 --requests 16 --num-pages 64 --max-pages 16 --page-size 8 \
    --warmup
# 6e. on-TPU TRACE CAPTURE + tracing-overhead A/B (first hardware
#     numbers for paddle_tpu.tracing): the Chrome-trace artifact gives
#     the first real per-phase TTFT decomposition on-chip
#     (serve_ttft_queue/prefill/gap_p50 — CPU-tiny gap shares are
#     prefill-dominated and say nothing about HBM-bound decode), and
#     the --trace-ab serve_trace_tpot_overhead record decides whether
#     tracing can default ON for serving configs (target: <= 1.02x).
#     Commit experiments/serve_trace_tpu.json with the session log.
STEP_TIMEOUT=2400 run python tools/serve_bench.py \
    --trace-out experiments/serve_trace_tpu.json --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
STEP_TIMEOUT=2400 run python tools/serve_bench.py --trace-ab --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
# 6f. on-TPU MULTI-REPLICA serve_bench (first hardware numbers for the
#     serving.Router fleet tier, after the 6e trace capture): 3
#     replica Servers on one chip (small pools so three engines fit),
#     replica 0 killed mid-run — read serve_fleet_survival_rate (must
#     stay 1.0), serve_failover_count, serve_failover_latency_p99,
#     serve_breaker_opens, and compare the 1-replica arm's TTFT
#     collapse vs the 3-replica arm (PERF.md "Fleet survival under
#     replica loss"; CPU-tiny reference: TTFT p50 3.62s -> 1.49s).
#     On-chip the rebuild window includes device reinit, so the
#     1-replica arm honestly shows the outage the CPU run understates.
STEP_TIMEOUT=2400 run python tools/serve_bench.py --router --replicas 1 \
    --kill-replica-at 2 --layers 2 --prompt-len 4:16 --max-new 12 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --seed 3
STEP_TIMEOUT=2400 run python tools/serve_bench.py --router --replicas 3 \
    --kill-replica-at 2 --layers 2 --prompt-len 4:16 --max-new 12 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --seed 3
# 6g. on-TPU QUANTIZED-KV serve_bench A/B (first hardware numbers for
#     int8 KV pages, after the 6f fleet run): identical load through
#     bf16 pools vs int8 pools at EQUAL HBM (the int8 arm gets 2x
#     pages automatically). Decode on TPU is HBM-bandwidth-bound, so
#     the halved page read bytes should convert into
#     serve_kv_quant_tpot_speedup here (CPU-tiny measured 1.15x but is
#     compute-bound — mechanism, not speedup); also read
#     serve_kv_quant_capacity_ratio (expect ~1.94x vs bf16),
#     serve_kv_occupancy_p99_int8 (~half the bf16 arm at matched
#     load), and the bounded-numerics records
#     serve_kv_quant_max_logit_div / serve_kv_quant_token_flips —
#     on-chip bf16 pools make the bf16 arm's baseline real (the CPU
#     arm stores f32).
STEP_TIMEOUT=2400 run python tools/serve_bench.py --kv-ab --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
# 6h. on-TPU MULTI-TENANT LoRA serve_bench A/B (after 6g): identical
#     pre-drawn zipf load through base (K=0) vs 8 resident rank-4
#     adapters — read serve_lora_tpot_overhead (CPU-tiny band was
#     1.01-1.06x; on HBM-bound TPU decode the bank-gather read is the
#     term to watch), serve_lora_mix_entropy (~2.17 bits expected),
#     and confirm zero post-warmup compiles in the jit counters (the
#     one-program-per-mix claim on hardware).
STEP_TIMEOUT=2400 run python tools/serve_bench.py --lora-ab \
    --adapter-dist zipf --layers 2 --prompt-len 8:24 --max-new 16 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --warmup
# 7. the remaining BASELINE.md configs — one window should produce the
#    full config table (VERDICT r4 Missing #3). Expected budgets: each
#    is a small model + cached-compile candidate; ~5-10 min warm,
#    ~20-30 min cold through the tunnel.
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py resnet
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py moe
STEP_TIMEOUT=3900 run env BENCH_WATCHDOG_SECS=3000 python bench.py vit
echo "=== session done; review $LOG, flip flags per PERF.md decision" \
     "rules, re-run bench.py, commit .autotune_cache.json ===" | tee -a "$LOG"
