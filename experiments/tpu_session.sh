#!/bin/bash
# TPU-session runbook (VERDICT r3 #1/#8, PERF.md attack plan) — run the
# moment the tunnel is up.
#
# RESUMABLE + PRIORITY-ORDERED (ROADMAP item 5's enabling refactor):
# every step has a NAME recorded in $STATE when it finishes (any rc —
# a failed step's log is still its harvest; delete its line to retry),
# so a 35-minute window RESUMES at the first unharvested step instead
# of replaying training parity from the top. SESSION_RESET=1 clears the
# state and starts over.
#
# ORDER (value-per-minute): the serving stack has NEVER touched a chip
# — every serve_bench number in PERF.md is CPU-tiny with explicit
# "mechanism, not speedup" caveats — so after the cheap preflights the
# serving-record steps (6c-6m) run FIRST, and the training-side parity
# replays and config benches come after. A window that dies at minute
# 35 should die owing training replays, not serving records.
#
# Every step appends to experiments/tpu_session.log; steps are
# independent — a failure moves on (the log is the evidence either way).
set -u
cd "$(dirname "$0")/.."
LOG=experiments/tpu_session.log
STATE=experiments/.tpu_session_state

if [ "${SESSION_RESET:-0}" = "1" ]; then
  rm -f "$STATE"
  echo "=== session state reset ===" | tee -a "$LOG"
fi
touch "$STATE"

run() {
  # Each step runs in its OWN process group (setsid) and the whole group
  # is SIGKILLed on timeout — `timeout` alone signals only the direct
  # child, and a remote-compile helper orphaned that way keeps holding
  # the device claim for every later step (observed 2026-07-31: exp_dots
  # and the autotune sweep hung >20min; killing the script leaked the
  # sweep process, which then wedged the claim for fresh probes).
  echo "=== $(date -u +%FT%TZ) $* (output -> $LOG; tail -f it)" \
    | tee -a "$LOG"
  setsid "$@" >>"$LOG" 2>&1 &
  local pid=$! t=${STEP_TIMEOUT:-2400} waited=0 rc
  while kill -0 "$pid" 2>/dev/null && [ "$waited" -lt "$t" ]; do
    sleep 5; waited=$((waited + 5))
  done
  if kill -0 "$pid" 2>/dev/null; then
    # TERM first: the bench/experiment watchdogs trap it to reap their
    # own detached children (which live in their OWN sessions and would
    # escape a bare group-KILL); KILL after a grace period
    kill -TERM -- "-$pid" 2>/dev/null
    local grace=0
    while kill -0 "$pid" 2>/dev/null && [ "$grace" -lt 15 ]; do
      sleep 1; grace=$((grace + 1))
    done
    kill -KILL -- "-$pid" 2>/dev/null
    rc=137
  else
    wait "$pid"; rc=$?
  fi
  echo "=== rc=$rc ===" | tee -a "$LOG"
  LAST_RC=$rc
}

step() {
  # step NAME cmd...: skip if NAME already harvested (in $STATE), else
  # run and record "NAME rc=N utc" on completion. A TIMED-OUT step
  # (rc=137) is recorded too — it already burned its budget once; to
  # force a retry next window, delete its line from $STATE.
  local name=$1; shift
  if grep -q "^${name} " "$STATE" 2>/dev/null; then
    echo "=== skip ${name} (harvested: $(grep "^${name} " "$STATE"))" \
      | tee -a "$LOG"
    return 0
  fi
  run "$@"
  echo "${name} rc=${LAST_RC} $(date -u +%FT%TZ)" >>"$STATE"
}

# ---------------------------------------------------------------------------
# 0. PREFLIGHTS (cheap, no device claim / tiny claim)
# ---------------------------------------------------------------------------
# 0a. invariant linter (~3s, CPU-only): a TPU window must never burn
#     minutes on a program that would recompile per request (PT001) or
#     block its scheduler gap on host syncs (PT002). Logs-and-continues,
#     but an unbaselined finding taints the window's serving records.
STEP_TIMEOUT=300 step lint python -m tools.lint paddle_tpu/ --summary
# 0b. QUICK kernel parity slice on real hardware (conftest escape
#     hatch): the bench-path shapes plus the r5 sub-lane modes. TIGHT
#     timeout — the serving records below must get their window even if
#     cold remote compiles are slow; the FULL parity suite is step 6b.
STEP_TIMEOUT=900 step kernel_slice env PADDLE_TPU_TESTS_ON_DEVICE=1 \
    python -m pytest tests/test_flash_attention.py \
    -k "device_scale or Sublane" -q -p no:cacheprovider

# ---------------------------------------------------------------------------
# SERVING RECORDS FIRST (6c-6m): nothing serving-side has ever run on a
# TPU; each step below converts one CPU-tiny "mechanism" number into a
# hardware record.
# ---------------------------------------------------------------------------
# 6c. FIRST on-chip online-serving records: the prefix-caching A/B is
#     the highest-value serving pair — TTFT p50/p99 + serve_kv_occupancy
#     + serve_prefix_hit_rate, cold then warm (PERF.md "Automatic prefix
#     caching"; 11.2x TTFT p50 on CPU tiny — the on-chip ratio decides
#     whether the cache defaults on for serving configs)
step serve_prefix_cold python tools/serve_bench.py \
    --shared-prefix-len 448 --cache-prefixes off --num-pages 320 \
    --max-pages 64 --page-size 8 --requests 16 --rate 4 --max-new 8 \
    --segment-steps 2 --prompt-len 4:8 --layers 2 --prefill-chunk 64 \
    --warmup
step serve_prefix_warm python tools/serve_bench.py \
    --shared-prefix-len 448 --cache-prefixes on --num-pages 320 \
    --max-pages 64 --page-size 8 --requests 16 --rate 4 --max-new 8 \
    --segment-steps 2 --prompt-len 4:8 --layers 2 --prefill-chunk 64 \
    --warmup
# 6d. on-TPU SPECULATIVE SERVING A/B (decode on TPU is HBM-bound, so
#     serve_spec_tokens_per_forward should convert into the TPOT ratio
#     here — the CPU wall ratio is honestly <1x)
step serve_spec_ab python tools/serve_bench.py --spec-ab --draft-k 6 \
    --repeat-unit 4 --layers 2 --prompt-len 28:32 --max-new 32 \
    --rate 8 --requests 16 --num-pages 64 --max-pages 16 --page-size 8 \
    --warmup
# 6e. on-TPU TRACE CAPTURE + tracing-overhead A/B (per-phase TTFT
#     decomposition on-chip; --trace-ab decides whether tracing can
#     default ON for serving configs, target <= 1.02x). Commit
#     experiments/serve_trace_tpu.json with the session log.
step serve_trace python tools/serve_bench.py \
    --trace-out experiments/serve_trace_tpu.json --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
step serve_trace_ab python tools/serve_bench.py --trace-ab --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
# 6f. on-TPU MULTI-REPLICA serve_bench: replica 0 killed mid-run — read
#     serve_fleet_survival_rate (must stay 1.0), failover count/latency,
#     breaker opens; the 1-replica arm honestly shows the outage the CPU
#     run understates (on-chip rebuild includes device reinit).
step serve_fleet_1rep python tools/serve_bench.py --router --replicas 1 \
    --kill-replica-at 2 --layers 2 --prompt-len 4:16 --max-new 12 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --seed 3
step serve_fleet_3rep python tools/serve_bench.py --router --replicas 3 \
    --kill-replica-at 2 --layers 2 --prompt-len 4:16 --max-new 12 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --seed 3
# 6g. on-TPU QUANTIZED-KV A/B at EQUAL HBM (int8 arm gets 2x pages):
#     HBM-bound decode should convert halved page bytes into
#     serve_kv_quant_tpot_speedup (CPU-tiny 1.19x is compute-bound
#     mechanism); also capacity_ratio (~1.94x) + bounded-numerics probes.
step serve_kv_ab python tools/serve_bench.py --kv-ab --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
# 6h. on-TPU MULTI-TENANT LoRA A/B: base (K=0) vs 8 resident rank-4
#     adapters on identical zipf load — serve_lora_tpot_overhead
#     (CPU-tiny band 1.01-1.06x; on HBM-bound decode the bank-gather
#     read is the term to watch), mix entropy ~2.17 bits, zero
#     post-warmup compiles in the jit counters.
step serve_lora_ab python tools/serve_bench.py --lora-ab \
    --adapter-dist zipf --layers 2 --prompt-len 8:24 --max-new 16 \
    --rate 8 --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --warmup
# 6i. on-TPU TENSOR-PARALLEL serving records (NEW — PR 14). Two halves:
#     (a) mechanism A/B at a size both arms fit — identical pre-drawn
#     load through TP=1 then TP=4; on ICI the per-block psums should be
#     near-free, so serve_tp_tpot_speedup tells what TP costs per token
#     (the CPU-mesh reference is 0.4x: host-mesh collectives, mechanism
#     only); (b) the capacity record — a 13B-preset engine at TP=4
#     serves while the SAME command at --tp 1 cannot load its weights
#     on one chip (run it once to log the OOM as evidence; that failure
#     is the claim). Raise --layers toward the full 40 as the window
#     allows; weights dominate, so even a truncated stack proves the
#     per-chip fit.
step serve_tp_ab python tools/serve_bench.py --tp-ab --tp 4 --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
STEP_TIMEOUT=3600 step serve_tp_13b python tools/serve_bench.py --tp 4 \
    --preset 13b --layers 8 --prompt-len 16:32 --max-new 16 --rate 4 \
    --requests 8 --num-pages 128 --max-pages 16 --page-size 8 --warmup
# 6j. on-TPU SLO/goodput capture + recording-overhead A/B (NEW — PR
#     15; queued after the 6i lora/tp records, no new device claims in
#     preflight). Two halves: (a) an SLO-scored multi-tenant run —
#     per-tenant goodput + the digest-exact serve_slo_ttft_p99 /
#     serve_slo_tpot_p99 (thresholds sized for on-chip decode:
#     CPU-tiny TPOT is ~ms-scale, TPU sub-ms — a miss here is real
#     headroom data, not noise); (b) --slo-ab on identical pre-drawn
#     load — the monitor+SLO recording path must hold the PR 8 bar
#     on-chip too (serve_slo_tpot_overhead <= 1.02x decides whether
#     SLO scoring defaults ON for serving configs).
step serve_slo python tools/serve_bench.py --slo-ttft 0.5 \
    --slo-tpot 0.05 --adapters 4 --adapter-dist zipf --layers 2 \
    --prompt-len 8:24 --max-new 16 --rate 8 --requests 24 \
    --num-pages 48 --max-pages 8 --page-size 8 --warmup
step serve_slo_ab python tools/serve_bench.py --slo-ab --layers 2 \
    --prompt-len 16:32 --max-new 16 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 16 --page-size 8 --warmup
# 6k. on-TPU program-ledger capture + regression gate (NEW — PR 16).
#     Three halves: (a) a ledger-on mixed-feature run writes the
#     /profile roofline snapshot — the FIRST on-chip per-program
#     MFU/bound table (PERF.md's dots-bucket headroom ranking,
#     derived by the instrument instead of by hand); (b) --profile-ab
#     on identical pre-drawn load — the one-bool bar on-chip
#     (serve_profile_tpot_overhead <= 1.05x decides whether the
#     ledger defaults ON for serving configs); (c) bench_diff against
#     the prior round's committed records — post-harvest, direction-
#     aware, rc recorded in the session log (nonzero = a metric
#     regressed >10% on-chip; read the REGRESSIONS table, don't
#     hand-compare).
step serve_profile python tools/serve_bench.py --profile \
    --profile-out PROFILE_TPU.json --adapters 4 --layers 2 \
    --shared-prefix-len 16 --prefill-chunk 16 --kv-dtype int8 \
    --speculative on --draft-k 4 --prompt-len 8:24 --max-new 16 \
    --rate 8 --requests 24 --num-pages 96 --max-pages 16 \
    --page-size 8 --warmup
step serve_profile_ab python tools/serve_bench.py --profile-ab \
    --layers 2 --prompt-len 16:32 --max-new 16 --rate 8 \
    --requests 16 --num-pages 64 --max-pages 16 --page-size 8 --warmup
step bench_diff python -m tools.bench_diff --dir .
# 6l. on-TPU CROSS-PROCESS FLEET records (NEW — PR 17). Two halves:
#     (a) the equal-silicon mono-vs-fleet A/B — 2 replica SUBPROCESSES
#     (each claiming its own device via the inherited environment)
#     against one double-size in-process server; on-chip the numbers
#     to read are serve_fleet_ttft_overhead (the HTTP hop + admission
#     probe per request) and serve_fleet_throughput_ratio (whether 2
#     schedulers beat 1 big batch at this rate — CPU reference: TTFT
#     ~2.1x, throughput ~0.52x, both dominated by the shared-core
#     tax a real 2-chip fleet doesn't pay); (b) the same A/B with a
#     replica process SIGKILLed mid-run — survival must stay 1.0
#     through failover replay + supervisor respawn, now priced with
#     on-chip device reinit in the respawn path. The disaggregated-
#     handoff byte-identity bar itself is tier-1 (tests/test_remote.py
#     runs on CPU); these steps put on-chip numbers on the topology.
STEP_TIMEOUT=3600 step serve_fleet_xproc python tools/serve_bench.py \
    --fleet 2 --layers 2 --prompt-len 4:16 --max-new 12 --rate 8 \
    --requests 24 --num-pages 48 --max-pages 8 --page-size 8 --warmup
STEP_TIMEOUT=3600 step serve_fleet_xproc_kill python tools/serve_bench.py \
    --fleet 2 --layers 2 --prompt-len 4:16 --max-new 12 --rate 8 \
    --requests 24 --num-pages 48 --max-pages 8 --page-size 8 \
    --kill-replica-at 2 --seed 3
# 6m. on-TPU DEVICE-RESIDENT SPECULATION A/B (NEW — PR 18): identical
#     repetitive load three ways — plain, host-mode spec (per-verify-
#     step proposer readback), device-mode spec (fused propose+verify+
#     accept segment program, ONE readback per segment). This is where
#     the sync elimination actually matters: on-chip each host-mode
#     verify step pays a full device->host->device round-trip the
#     fused program doesn't. Read serve_spec_mode_tpot_speedup (the
#     host/device TPOT ratio — CPU reference ~0.9x, mechanism only;
#     on-chip >1x is the PR's latency claim) and the receipt pair
#     serve_spec_host_syncs_per_token_{spec,specdev} (device arm MUST
#     print 0.0 on-chip too — a nonzero there means a hidden sync
#     crept into the fused path). tokens/forward and acceptance must
#     match across the spec arms (same drafts, same acceptance math);
#     the 6k bench_diff gate picks all of these up next round.
step serve_spec_device_ab python tools/serve_bench.py --spec-ab \
    --spec-mode device --draft-k 6 --repeat-unit 4 --layers 2 \
    --prompt-len 16:24 --max-new 24 --rate 8 --requests 16 \
    --num-pages 64 --max-pages 8 --page-size 8 --warmup

# 6n. on-TPU OVERLOAD-CONTROL A/B (NEW — PR 19): three arms — cap at
#     --rate, then the identical pre-drawn 60%-hot-tenant load at 2x
#     that rate without/with Server(control_policy=...). CALIBRATE
#     FIRST: run one plain arm at a high rate to find the chip's
#     req/s capacity, set --rate to ~0.85x of it and --slo-ttft to
#     ~3x the at-capacity TTFT p99 (the CPU reference used 4 req/s /
#     1.5s against a ~4.6 req/s toy; see PERF.md round 19 for the
#     knob-sensitivity notes — max_queue should let occupancy lead
#     burn by a second or two). The bar is the CPU one: ctrlon
#     cold-tenant goodput retention >= 0.9 with nonzero hot sheds,
#     ctrloff collapsing. Mechanism is chip-independent host
#     bookkeeping; what TPU adds is REAL HBM-bound service times
#     under the brownout max_new cap.
STEP_TIMEOUT=3600 step serve_overload_ab python tools/serve_bench.py \
    --overload-ab --requests 240 --rate 4 --max-new 96 --max-batch 1 \
    --layers 6 --max-queue 16 --slo-ttft 1.5 --warmup

# 6o. on-TPU WIRE-CHAOS A/B (NEW — PR 20): identical pre-drawn load
#     over the real HTTP wire, clean vs injected delay/drop/half-
#     close/corrupt at the generate + kv_import seams. The bar is
#     exactly-once survival: serve_wire_survival_rate == 1.0 (every
#     chaos-arm request's tokens bitwise-match the clean arm's) with
#     nonzero resumes/retries and every corrupt KV ship rejected
#     before install then re-shipped clean. Mechanism is chip-
#     independent; what TPU adds is real page bytes in the shipped
#     payloads (digests over device-exported pools, not toy arrays).
STEP_TIMEOUT=3600 step serve_wire_chaos python tools/serve_bench.py \
    --wire-chaos --layers 2 --prompt-len 4:16 --max-new 12 --rate 8 \
    --requests 16 --num-pages 64 --max-pages 8 --page-size 8 \
    --cache-prefixes on --warmup

# ---------------------------------------------------------------------------
# TRAINING-SIDE PARITY + PERF LEVERS (after the serving records)
# ---------------------------------------------------------------------------
# 2. round record (bench has its own group-killing watchdog: accelerator
#    attempt BENCH_WATCHDOG_SECS then a 600s CPU retry — keep the outer
#    step timeout above their sum so the CPU retry can finish)
STEP_TIMEOUT=3900 step bench_round env BENCH_WATCHDOG_SECS=3000 \
    python bench.py
# 3. flag-deciding experiments (cheap compiles, decide defaults)
step exp_flash_hb python experiments/exp_flash_hb.py
# exp_dots: 8 variants x EXP_VARIANT_SECS(600) worst case — the step
# timeout must cover the per-variant budgets, not fight them
STEP_TIMEOUT=5100 step exp_dots python experiments/exp_dots.py
# 4. lever A/B on the full bench (log evidence, not the round record;
#    flip a default in code only on a >=3% full-step win per PERF.md)
STEP_TIMEOUT=3900 step bench_remat env BENCH_WATCHDOG_SECS=3000 \
    BENCH_REMAT=attn_out python bench.py
STEP_TIMEOUT=3900 step bench_unroll env BENCH_WATCHDOG_SECS=3000 \
    BENCH_SCAN_UNROLL=2 python bench.py
# 5. autotune sweep -> .autotune_cache.json (commit it); 5 trials x
#    EXP_TRIAL_SECS(900)
STEP_TIMEOUT=4800 step autotune_sweep python experiments/exp_autotune_sweep.py
# 6. bigger configs (cold-cache compiles can be slow through the tunnel)
STEP_TIMEOUT=3900 step bench_1b3 env BENCH_WATCHDOG_SECS=3000 \
    python bench.py 1.3b
# 6b. FULL kernel parity on-chip (the quick slice in step 0b covered the
#     bench path; this covers everything else incl. the head-batched
#     kernel, whose device routing stays off until green + measured win)
step kernel_full env PADDLE_TPU_TESTS_ON_DEVICE=1 PADDLE_TPU_HB_ON_DEVICE=1 \
    python -m pytest \
    tests/test_flash_attention.py tests/test_flash_hb.py \
    tests/test_pallas_kernels.py tests/test_paged_attention.py \
    -q -p no:cacheprovider
STEP_TIMEOUT=3900 step bench_ragged env BENCH_WATCHDOG_SECS=3000 \
    python bench.py ragged
STEP_TIMEOUT=3900 step bench_decode env BENCH_WATCHDOG_SECS=3000 \
    python bench.py decode
# speculative decode: tokens/forward + WALL speedup (decode is HBM-bound
# on TPU, so unlike the CPU fallback the wall number should track the
# tokens/forward ratio)
STEP_TIMEOUT=3900 step bench_spec env BENCH_WATCHDOG_SECS=3000 \
    python bench.py spec
# 7. the remaining BASELINE.md configs — one window should produce the
#    full config table (VERDICT r4 Missing #3). Expected budgets: each
#    is a small model + cached-compile candidate; ~5-10 min warm,
#    ~20-30 min cold through the tunnel.
STEP_TIMEOUT=3900 step bench_resnet env BENCH_WATCHDOG_SECS=3000 \
    python bench.py resnet
STEP_TIMEOUT=3900 step bench_moe env BENCH_WATCHDOG_SECS=3000 \
    python bench.py moe
STEP_TIMEOUT=3900 step bench_vit env BENCH_WATCHDOG_SECS=3000 \
    python bench.py vit
echo "=== session done; review $LOG, flip flags per PERF.md decision" \
     "rules, re-run bench.py, commit .autotune_cache.json;" \
     "$STATE holds the harvest ledger (delete a line to retry) ===" \
     | tee -a "$LOG"
