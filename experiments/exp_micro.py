"""Component microbenchmarks at bench shapes (350M llama, b8 s2048).

Times each building block with a carry-dependent loop (no loop-invariant
hoisting). Run: python experiments/exp_micro.py [name ...]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timed(fn, args, iters=20):
    import jax
    import jax.numpy as jnp

    # chain: perturb first arg by a tiny nonzero function of the output so
    # XLA can neither hoist the body (loop-variant input) nor simplify the
    # add away (x + 0 would fold; x + s*1e-30 does not)
    def loop(args, n):
        def body(_, a):
            out = fn(*a)
            s = jax.tree.map(lambda x: jnp.sum(x).astype(jnp.float32), out)
            tot = jax.tree.reduce(lambda p, q: p + q, s) * 1e-30
            return (a[0] + tot.astype(a[0].dtype),) + tuple(a[1:])

        out = jax.lax.fori_loop(0, n, body, args)
        # scalar result: host readback is the only honest barrier through
        # the remote-dispatch tunnel (block_until_ready returns early)
        return jnp.sum(out[0].astype(jnp.float32).ravel()[:128])

    jit = jax.jit(loop, static_argnums=(1,))
    # two iteration counts; the difference cancels the constant dispatch +
    # tunnel-readback cost that otherwise dominates sub-ms ops
    lo, hi = iters, iters * 6
    _ = float(jit(args, lo))
    _ = float(jit(args, hi))
    t0 = time.perf_counter()
    _ = float(jit(args, lo))
    t1 = time.perf_counter()
    _ = float(jit(args, hi))
    t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (hi - lo)


def main(names):
    import jax
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    B, S, H, D, HID, FF, V, L = 8, 2048, 8, 128, 1024, 2816, 32000, 24
    key = jax.random.PRNGKey(0)
    bf = jnp.bfloat16

    results = {}

    def rec(name, t, flops=None):
        r = {"ms": round(t * 1e3, 3)}
        if flops:
            r["tflops"] = round(flops / t / 1e12, 1)
            r["mxu_pct"] = round(100 * flops / t / 394e12, 1)
        results[name] = r
        print(json.dumps({name: r}), flush=True)

    x = jax.random.normal(key, (B * S, HID), bf)
    w1 = jax.random.normal(key, (HID, FF), bf)

    if "matmul" in names:
        t = timed(lambda a, b: a @ b, (x, w1))
        rec("matmul_16k_1024_2816", t, 2 * B * S * HID * FF)

    if "matmul_vocab" in names:
        wv = jax.random.normal(key, (HID, V), bf)
        t = timed(lambda a, b: a @ b, (x, wv))
        rec("matmul_16k_1024_32000", t, 2 * B * S * HID * V)

    q = jax.random.normal(key, (B, S, H, D), bf)
    k = jax.random.normal(key, (B, S, H, D), bf)
    v = jax.random.normal(key, (B, S, H, D), bf)
    # causal attention FLOPs (fwd): 2*2*B*H*S^2*D / 2
    att_flops = 2 * B * H * S * S * D

    if "flash_fwd" in names:
        from paddle_tpu.ops.pallas import flash_attention

        t = timed(lambda q, k, v: flash_attention(q, k, v, causal=True),
                  (q, k, v))
        rec("flash_fwd", t, att_flops)

    if "flash_bwd" in names:
        from paddle_tpu.ops.pallas import flash_attention

        def fb(q, k, v):
            def f(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal=True).astype(jnp.float32))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        t = timed(fb, (q, k, v))
        rec("flash_fwd+bwd", t, 3 * att_flops)

    if "xla_attn" in names:
        def sdpa(q, k, v):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, v,
                           preferred_element_type=jnp.float32)
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask, s / np.sqrt(D), -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(bf)
            return jnp.einsum("bhqk,bkhd->bqhd", p, k)

        t = timed(sdpa, (q, k, v))
        rec("xla_sdpa_fwd", t, att_flops)

    if "rms" in names:
        from paddle_tpu.models.llama_functional import _rms

        xh = jax.random.normal(key, (B, S, HID), bf)
        w = jnp.ones((HID,), bf)
        t = timed(lambda a, b: _rms(a, b, 1e-5), (xh, w))
        rec("rms_norm", t)

    if "rope" in names:
        from paddle_tpu.models.llama import _rope_cos_sin, apply_rotary_emb

        cos, sin = _rope_cos_sin(S, D, 10000.0, bf)
        t = timed(lambda a: apply_rotary_emb(a, cos, sin), (q,))
        rec("rope", t)

    if "loss" in names:
        logits = jax.random.normal(key, (B, S, V), bf)
        lbl = jnp.zeros((B, S), jnp.int32)

        def ce(lg, lb):
            lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(lp, lb[..., None], -1)[..., 0]
            return jnp.mean(nll)

        t = timed(ce, (logits, lbl))
        rec("ce_loss_fwd", t)

    if "layer_fwd" in names:
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.models.llama_functional import _layer_fwd
        from paddle_tpu.models.llama import _rope_cos_sin

        cfg = LlamaConfig(hidden_size=HID, intermediate_size=FF,
                          num_hidden_layers=1, num_attention_heads=H,
                          num_key_value_heads=H, vocab_size=V,
                          dtype="bfloat16")
        cos, sin = _rope_cos_sin(S, cfg.head_dim, cfg.rope_theta, bf)
        lp = {
            "input_layernorm.weight": jnp.ones((HID,), bf),
            "post_attention_layernorm.weight": jnp.ones((HID,), bf),
            "self_attn.q_proj.weight": jax.random.normal(key, (HID, HID), bf) * 0.02,
            "self_attn.k_proj.weight": jax.random.normal(key, (HID, HID), bf) * 0.02,
            "self_attn.v_proj.weight": jax.random.normal(key, (HID, HID), bf) * 0.02,
            "self_attn.o_proj.weight": jax.random.normal(key, (HID, HID), bf) * 0.02,
            "mlp.gate_proj.weight": jax.random.normal(key, (HID, FF), bf) * 0.02,
            "mlp.up_proj.weight": jax.random.normal(key, (HID, FF), bf) * 0.02,
            "mlp.down_proj.weight": jax.random.normal(key, (FF, HID), bf) * 0.02,
        }
        xh = jax.random.normal(key, (B, S, HID), bf)
        t = timed(lambda a: _layer_fwd(lp, a, cos, sin, cfg), (xh,))
        layer_flops = 2 * B * S * (4 * HID * HID + 3 * HID * FF) + att_flops
        rec("decoder_layer_fwd", t, layer_flops)

    print(json.dumps(results))


ALL = ["matmul", "matmul_vocab", "flash_fwd", "flash_bwd", "xla_attn",
       "rms", "rope", "loss", "layer_fwd"]

if __name__ == "__main__":
    main(sys.argv[1:] or ALL)
