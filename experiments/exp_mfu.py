"""MFU experiment matrix for the bench config (350M llama, v5e).

Run: python experiments/exp_mfu.py [name ...]   (default: all)
Each config prints one JSON line; compare mfu across remat policy / batch.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(name, remat, batch, seq=2048, steps=10, fwd_only=False):
    import jax
    import jax.numpy as jnp

    cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.models.llama_functional import (build_train_step,
                                                    build_loss_fn,
                                                    stack_params)

    cfg = llama_config("350m", dtype="bfloat16",
                       num_attention_heads=8, num_key_value_heads=8,
                       max_position_embeddings=seq, recompute="full")
    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    stacked, rest = stack_params(params, cfg)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)

    if fwd_only:
        loss_fn = build_loss_fn(cfg, remat=remat)

        def multi(stacked, rest, ids, labels, n):
            def body(_, acc):
                return acc + loss_fn(stacked, rest, ids, labels)
            return jax.lax.fori_loop(0, n, body, jnp.zeros((), jnp.float32))

        jitted = jax.jit(multi, static_argnums=(4,))
        args = (stacked, rest, ids, labels, steps)
        out = jitted(*args); _ = float(out)
        t0 = time.perf_counter()
        out = jitted(*args); _ = float(out)
        dt = time.perf_counter() - t0
        flops_per_tok = 2.0 * n_params
    else:
        step, init = build_train_step(cfg, lr=1e-4, remat=remat)
        opt_state = init(stacked, rest)

        def multi(stacked, rest, st, ids, labels, n):
            def body(_, carry):
                stacked, rest, st, _ = carry
                stacked, rest, st, loss = step(stacked, rest, st, ids, labels)
                return stacked, rest, st, loss.astype(jnp.float32)
            return jax.lax.fori_loop(0, n, body,
                                     (stacked, rest, st,
                                      jnp.zeros((), jnp.float32)))

        jitted = jax.jit(multi, static_argnums=(5,), donate_argnums=(0, 1, 2))
        stacked, rest, opt_state, loss = jitted(stacked, rest, opt_state,
                                                ids, labels, steps)
        _ = float(loss)
        t0 = time.perf_counter()
        stacked, rest, opt_state, loss = jitted(stacked, rest, opt_state,
                                                ids, labels, steps)
        _ = float(loss)
        dt = time.perf_counter() - t0
        flops_per_tok = 6.0 * n_params

    tokens = batch * seq * steps
    peak = 394e12
    mfu = flops_per_tok * tokens / dt / peak
    print(json.dumps({"exp": name, "remat": str(remat), "batch": batch,
                      "tps": round(tokens / dt, 1), "mfu": round(mfu, 4),
                      "dt": round(dt, 3)}), flush=True)


CONFIGS = {
    "base": dict(remat="full", batch=8),
    "dots": dict(remat="dots", batch=8),
    "none": dict(remat="none", batch=8),
    "b16_full": dict(remat="full", batch=16),
    "b16_dots": dict(remat="dots", batch=16),
    "fwd_full": dict(remat="full", batch=8, fwd_only=True),
    "fwd_none": dict(remat="none", batch=8, fwd_only=True),
}

if __name__ == "__main__":
    names = sys.argv[1:] or list(CONFIGS)
    for n in names:
        try:
            run(n, **CONFIGS[n])
        except Exception as e:
            print(json.dumps({"exp": n, "error": str(e)[:300]}), flush=True)
