"""Shared spawn-with-budget harness for anything that talks to the TPU
tunnel (bench watchdog, exp_dots variants, autotune-sweep trials).

One implementation on purpose: the 2026-07-31 session showed three
failure modes — a mid-compile remote-transport hang, a killed parent
orphaning its child (which then held the device claim and wedged every
later probe), and SIGKILL-only cleanup that untrappably skipped child
reaping.  The rules encoded here:

- the child runs in its OWN session (``start_new_session=True``) so the
  whole process tree can be killed as a group;
- on budget expiry the group gets SIGTERM, a grace period to reap its
  own children, then SIGKILL;
- while the child runs, this process forwards an incoming SIGTERM to
  the child group before dying, so an OUTER timeout can never orphan
  the tree;
- partial stdout/stderr is salvaged on every path — it is the only
  evidence of where a hang happened.
"""
from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import List, NamedTuple


class BudgetResult(NamedTuple):
    out: str
    err: str
    returncode: int  # -9 when group-killed
    timed_out: bool


def _killpg(pid: int, sig: int) -> None:
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError):
        pass


def _term_then_kill(pid: int, grace: float = 10.0) -> None:
    """SIGTERM the group, give it ``grace`` seconds to reap its own
    children (a trapped TERM is how the bench watchdog kills ITS
    detached child), then SIGKILL.  Liveness is probed with signal 0 —
    never ``waitpid``, which would steal the exit status from the Popen
    that owns the child (a lingering zombie just burns the grace)."""
    _killpg(pid, signal.SIGTERM)
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.2)
    _killpg(pid, signal.SIGKILL)


def run_budgeted(cmd: List[str], budget: float,
                 env: dict = None) -> BudgetResult:
    """Run ``cmd`` in its own session with a wall-clock budget; never
    orphan its process tree, even when this process is SIGTERMed."""
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True,
                         start_new_session=True)

    def _forward(signum, frame, _pid=p.pid):
        _term_then_kill(_pid, grace=5.0)
        raise SystemExit(128 + signum)

    prev = signal.signal(signal.SIGTERM, _forward)
    timed_out = False
    try:
        try:
            out, err = p.communicate(timeout=budget)
        except subprocess.TimeoutExpired:
            timed_out = True
            _term_then_kill(p.pid)
            out, err = p.communicate()  # partial buffers — the evidence
    except BaseException:  # Ctrl-C etc.: never orphan the claim
        _killpg(p.pid, signal.SIGKILL)
        raise
    finally:
        signal.signal(signal.SIGTERM, prev)
        if p.poll() is None:
            _killpg(p.pid, signal.SIGKILL)
    return BudgetResult(out or "", err or "", p.returncode, timed_out)
