"""Flash-attention kernel block-size sweep at bench shapes on TPU.

Run: python experiments/exp_flash.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from exp_micro import timed
    from paddle_tpu.ops.flash_attention_kernel import flash_attention_bhsd

    B, H, S, D = 8, 8, 2048, 128
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)
    att_flops = 2 * B * H * S * S * D  # fwd, non-causal count

    for bq, bk in [(512, 512), (256, 512), (512, 256), (1024, 512),
                   (512, 1024), (1024, 1024), (256, 1024), (2048, 512),
                   (512, 2048), (128, 512)]:
        try:
            def f(q, k, v):
                return flash_attention_bhsd(q, k, v, causal=True,
                                            block_q=bq, block_k=bk)

            t = timed(f, (q, k, v), iters=10)

            def fb(q, k, v):
                def g(q, k, v):
                    return jnp.sum(flash_attention_bhsd(
                        q, k, v, causal=True, block_q=bq,
                        block_k=bk).astype(jnp.float32))
                return jax.grad(g, argnums=(0, 1, 2))(q, k, v)

            tb = timed(fb, (q, k, v), iters=10)
            print(json.dumps({
                "bq": bq, "bk": bk,
                "fwd_ms": round(t * 1e3, 3),
                "fwd_mxu_pct": round(100 * att_flops / t / 394e12, 1),
                "fwdbwd_ms": round(tb * 1e3, 3),
                "fwdbwd_mxu_pct": round(100 * 3 * att_flops / tb / 394e12,
                                        1)}), flush=True)
        except Exception as e:
            print(json.dumps({"bq": bq, "bk": bk,
                              "error": str(e)[:120]}), flush=True)


if __name__ == "__main__":
    main()
