"""Benchmark driver: one JSON line for the round record.

Measures flagship-model (Llama-family) training throughput on the available
chip: tokens/sec/chip and MFU (model FLOPs 6·N·tokens / peak). North star
(BASELINE.md): ≥50% MFU — `vs_baseline` reports MFU/0.50 so 1.0 == target
(the reference publishes no absolute numbers, BASELINE.json "published": {}).

Run: python bench.py            (real TPU under axon; CPU fallback = tiny cfg)
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _devices_or_cpu_fallback():
    """jax.devices() with a CPU fallback when the TPU tunnel is wedged.

    A stale remote claim makes backend init raise/hang; a degraded CPU
    record beats a crashed round record (round 1's bench signal was rc=1).
    The init attempt runs in a subprocess so a HANG (not just an error)
    also falls back. Also wires the persistent compile cache — EVERY
    bench mode recompiles a multi-minute program through the remote
    helper otherwise."""
    import os
    import subprocess
    import sys

    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    if os.environ.get("BENCH_FORCE_CPU"):
        # watchdog retry path: the TPU attempt hung mid-compile (remote
        # transport death, seen 2026-07-31) — record honestly from CPU
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()
    cfg_platforms = str(getattr(jax.config, "jax_platforms", "") or
                        os.environ.get("JAX_PLATFORMS", ""))
    if cfg_platforms == "cpu":
        return jax.devices()  # already CPU-pinned: nothing to probe
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True,
            timeout=None if os.environ.get("BENCH_NO_PROBE_TIMEOUT")
            else 180)
        ok = probe.returncode == 0 and "ok" in probe.stdout
        why = f"rc={probe.returncode}"
    except subprocess.TimeoutExpired:
        ok, why = False, "init hang >180s"
    if ok:
        return jax.devices()
    print(f'{{"warning": "accelerator init failed ({why}); '
          'falling back to CPU"}}'.replace("}}", "}"), file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()


def main(model_size: str = "350m"):
    import os

    import jax

    if model_size not in ("350m", "1.3b"):
        raise SystemExit(
            f"unknown model size {model_size!r} (350m|1.3b) — refusing to "
            f"mislabel a benchmark record")

    # persistent compile cache: bench iterations recompile a ~20-min XLA
    # program otherwise (remote-compile helper has no cross-run cache)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.models.llama_functional import (build_train_step,
                                                    stack_params)

    moment_dtype = None
    if on_tpu:
        # 350M-param Llama with head_dim 128 (8 heads x 128 instead of
        # 16 x 64): same parameter count, full-width MXU lanes on the
        # attention contractions. Full activation recompute bounds live
        # activations to one layer's worth (round-1 bench OOMed without it).
        if model_size == "1.3b":
            # BASELINE config 2 scale on ONE chip: bf16 FIRST moment
            # (v must stay fp32 — 1-beta2 is below the bf16 ulp and the
            # stored v would freeze) + batch 4; fp32 moments alone were
            # the r2 OOM (10.4GB)
            import jax.numpy as jnp

            cfg = llama_config("1b3", dtype="bfloat16",
                               max_position_embeddings=2048,
                               recompute="full")
            batch, seq, steps = 4, 2048, 20
            moment_dtype = jnp.bfloat16
        else:
            cfg = llama_config("350m", dtype="bfloat16",
                               num_attention_heads=8, num_key_value_heads=8,
                               max_position_embeddings=2048,
                               recompute="full")
            # >= 50 steps: the r4 record was a 10-step snapshot; a
            # steady-state window (~20 s at 400 ms/step) makes the
            # tokens/s and MFU numbers robust to warmup/dispatch noise
            batch, seq, steps = 8, 2048, 50
        # shared per-generation peak table (device/peaks.py — the same
        # denominator the serving ledger's MFU uses, so training-bench
        # MFU and per-program MFU stay comparable; numbers for the
        # recorded generations are unchanged from earlier rounds)
        from paddle_tpu.device import peaks as _peaks

        peak = _peaks.peaks()["peak_flops"]
    else:
        cfg = llama_config("tiny")
        batch, seq, steps = 4, 128, 3
        peak = 1e12  # meaningless on CPU; MFU reported but not comparable

    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    # scan-over-layers functional form: the decoder layer compiles ONCE
    # regardless of depth (an inlined 24-layer remat+vjp HLO took the
    # remote compile helper >40 min; this compiles in ~1 min)
    stacked, rest = stack_params(params, cfg)
    # BENCH_REMAT (full|attn_out|none) / BENCH_SCAN_UNROLL: the exp_dots
    # E1/E5 levers, env-switchable so a TPU session can A/B the full
    # bench without code edits; defaults match the recorded baseline
    remat = os.environ.get("BENCH_REMAT", "full")  # _remat_policy vocab
    step, init = build_train_step(
        cfg, lr=1e-4, remat=remat, moment_dtype=moment_dtype,
        scan_unroll=int(os.environ.get("BENCH_SCAN_UNROLL", "1")))
    opt_state = init(stacked, rest)

    # ONE dispatch for the whole timed loop (lax.fori_loop inside jit): the
    # remote-tunnel dispatch latency would otherwise dominate, and
    # block_until_ready is not an honest barrier through the tunnel — a
    # scalar host readback is.
    def multi_step(stacked, rest, st, ids, labels, n):
        import jax.numpy as jnp

        def body(_, carry):
            stacked, rest, st, _ = carry
            stacked, rest, st, loss = step(stacked, rest, st, ids, labels)
            return stacked, rest, st, loss.astype(jnp.float32)

        return jax.lax.fori_loop(0, n, body,
                                 (stacked, rest, st,
                                  jnp.zeros((), jnp.float32)))

    jitted = jax.jit(multi_step, static_argnums=(5,),
                     donate_argnums=(0, 1, 2))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)

    # warmup / compile with the SAME static n as the timed call
    stacked, rest, opt_state, loss = jitted(stacked, rest, opt_state, ids,
                                            labels, steps)
    _ = float(loss)  # host readback barrier

    t0 = time.perf_counter()
    stacked, rest, opt_state, loss = jitted(stacked, rest, opt_state, ids,
                                            labels, steps)
    loss_val = float(loss)  # host readback barrier
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tps = tokens / dt
    model_flops = 6.0 * n_params * tokens  # fwd+bwd ≈ 6·N per token
    mfu = model_flops / dt / peak
    rec = {
        "metric": f"llama_{model_size if on_tpu else 'tiny'}"
                  "_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "params": n_params,
        "platform": platform,
        "final_loss": loss_val,
        "steps": steps,
        "batch": batch,
        "seq": seq,
    }
    try:
        # which flash sub-lane plan this config's head_dim rides (the r4
        # record's comparability problem: a silent fp32 upcast at hd<128
        # would not be the same benchmark — surface it in the record)
        import jax.numpy as jnp

        from paddle_tpu.ops.flash_attention_kernel import _sublane_plan

        hd = cfg.hidden_size // cfg.num_attention_heads
        smode, dpad = _sublane_plan(
            hd, jnp.bfloat16 if on_tpu else jnp.float32, not on_tpu)
        rec["flash_sublane"] = {"head_dim": hd, "mode": smode or "native",
                                "dpad": dpad}
    except Exception:
        pass
    if not on_tpu:
        # a CPU fallback record is a MISSING TPU number, not a result —
        # attach the round's probe history and the hardware-free evidence
        # (config-3 compile-only memory fits) so the record is legible
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            import glob as _glob
            import re as _re

            def _round_no(p):
                m = _re.search(r"_r(\d+)\.log$", p)
                return int(m.group(1)) if m else -1

            logs = sorted(_glob.glob(os.path.join(here,
                                                  "TPU_PROBES_r*.log")),
                          key=_round_no)
            if logs:
                lines = open(logs[-1]).read().strip().splitlines()
                rec["tpu_probes"] = {"file": os.path.basename(logs[-1]),
                                     "attempts": len(lines),
                                     "last": lines[-1] if lines else ""}
        except OSError:
            pass
        try:
            mem = json.load(open(os.path.join(here,
                                              "MEMORY_CONFIG3.json")))
            rec["config3_memory_fits"] = [
                {"model": m.get("model"), "stash": m.get("stash"),
                 "zero_stage": m.get("zero_stage"),
                 "peak_gib": m.get("peak_gib"),
                 "fits": m.get("fits", False)} for m in mem]
        except (OSError, ValueError):
            pass
        try:
            # the last REAL-hardware record this repo captured (written
            # by a TPU session from its own bench output, committed with
            # provenance) — clearly labeled: it is NOT this run's number
            rec["tpu_session_record"] = json.load(
                open(os.path.join(here, "TPU_SESSION_RECORD.json")))
        except (OSError, ValueError):
            pass
    try:
        # provenance header: which machine/backend/rev produced this
        # number — tools/bench_diff.py warns when two compared rounds'
        # env headers disagree (cross-machine MFU is not a comparison)
        from paddle_tpu.monitor.provenance import env_stamp

        rec["env"] = env_stamp()
    except Exception:
        pass
    print(json.dumps(rec))


def spec_bench():
    """Speculative-decode measurement: tokens emitted per model forward
    (lossless n-gram lookup, greedy) and wall tokens/s vs the plain
    decode loop on the same prompt. Run: python bench.py spec.

    The reference has no speculative path; on TPU decode is HBM-bound,
    so tokens_per_forward approximates the end-to-end speedup on
    accepting inputs. A code-like self-repetitive prompt is used — the
    accepting case this path exists for — alongside a random prompt as
    the adversarial floor (ratio ~1)."""
    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    from paddle_tpu.inference.generation import (CausalLMEngine,
                                                 GenerationConfig)
    from paddle_tpu.models import LlamaForCausalLM, llama_config

    if on_tpu:
        cfg = llama_config("350m", dtype="bfloat16", num_attention_heads=8,
                           num_key_value_heads=8)
        prompt_unit, reps, new, max_len, k = 16, 16, 256, 1024, 8
    else:
        cfg = llama_config("tiny")
        prompt_unit, reps, new, max_len, k = 4, 8, 32, 256, 6
    model = LlamaForCausalLM(cfg)
    model.eval()
    eng = CausalLMEngine(model, max_batch=1, max_len=max_len)
    rng = np.random.RandomState(0)
    unit = rng.randint(0, cfg.vocab_size, (prompt_unit,))
    rep_prompt = np.tile(unit, reps)[None].astype(np.int32)
    gc = GenerationConfig(max_new_tokens=new, do_sample=False,
                          eos_token_id=None)
    # warm both paths (compiles), then time one run each
    ref = eng.generate(rep_prompt, gc)
    spec = eng.generate_speculative(rep_prompt, gc, draft_k=k)
    exact = bool(np.array_equal(ref, spec))
    t0 = time.perf_counter()
    eng.generate(rep_prompt, gc)
    t_plain = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.generate_speculative(rep_prompt, gc, draft_k=k)
    t_spec = time.perf_counter() - t0
    tpf_rep = eng.last_spec_stats["tokens_per_forward"]
    rand_prompt = rng.randint(0, cfg.vocab_size,
                              (1, prompt_unit * reps)).astype(np.int32)
    # same shapes/draft_k as the repetitive leg: already compiled, and
    # tokens_per_forward is deterministic — one run suffices
    eng.generate_speculative(rand_prompt, gc, draft_k=k)
    tpf_rand = eng.last_spec_stats["tokens_per_forward"]
    print(json.dumps({
        "metric": "speculative_tokens_per_forward"
                  + ("" if on_tpu else "_tiny"),
        "value": round(tpf_rep, 3), "unit": "tokens/forward (repetitive)",
        "vs_baseline": round(tpf_rep, 3),   # plain decode is 1.0
        "tokens_per_forward_random": round(tpf_rand, 3),
        "exact_match_vs_generate": exact,
        "wall_speedup_repetitive": round(t_plain / max(t_spec, 1e-9), 3),
        "platform": platform}))


def decode_bench():
    """BASELINE config 5: decode throughput over the KV-cache engine
    (reference fused_multi_transformer decode loop). Run: python bench.py
    decode. Prints one JSON line with tokens/s across the decode scan."""
    import jax

    import numpy as np

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    from paddle_tpu.inference.generation import (CausalLMEngine,
                                                 GenerationConfig)
    from paddle_tpu.models import LlamaForCausalLM, llama_config

    if on_tpu:
        cfg = llama_config("350m", dtype="bfloat16", num_attention_heads=8,
                           num_key_value_heads=8)
        batch, prompt, new = 8, 128, 256
        max_len = 512
    else:
        cfg = llama_config("tiny")
        batch, prompt, new = 2, 16, 16
        max_len = 64
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = sum(int(np.prod(p.shape)) for _, p in model.named_parameters())
    eng = CausalLMEngine(model, max_batch=batch, max_len=max_len)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    gc = GenerationConfig(max_new_tokens=new)
    out = eng.generate(ids, gc)          # warm/compile
    t0 = time.perf_counter()
    out = eng.generate(ids, gc)
    dt = time.perf_counter() - t0
    toks = batch * new
    rec = {
        "metric": "llama_350m_decode_tokens_per_sec" if on_tpu
        else "llama_tiny_decode_tokens_per_sec",
        "value": round(toks / dt, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # no published reference decode number
        "params": n_params,
        "batch": batch,
        "platform": platform,
    }
    print(json.dumps(rec))


def resnet_bench():
    """BASELINE config 1: ResNet-50 single-device training imgs/sec.
    Run: python bench.py resnet."""
    import jax
    import jax.numpy as jnp

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    import paddle_tpu as paddle
    from paddle_tpu.nn.functional_call import functional_call
    from paddle_tpu.optimizer.functional import adamw_init, adamw_update
    from paddle_tpu.vision.models import resnet18, resnet50

    if on_tpu:
        model = resnet50()
        batch, steps, hw = 64, 10, 224
    else:
        model = resnet18()
        batch, steps, hw = 2, 2, 32
    model.train()
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())

    def loss_fn(pv, x, y):
        out = functional_call(model, pv, paddle.Tensor(x))
        out = out.value if hasattr(out, "value") else out
        logp = jax.nn.log_softmax(out.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))

    # ONE dispatch for the whole timed loop (same pattern as the llama
    # bench): per-call dispatch + the ~38MB image upload through the remote
    # tunnel would otherwise dominate the measurement
    def multi_step(pv, st, x, y, n):
        def body(_, carry):
            pv, st, _ = carry
            loss, g = jax.value_and_grad(loss_fn)(pv, x, y)
            st, pv = adamw_update(g, st, pv, lr=1e-3)
            return pv, st, loss.astype(jnp.float32)

        return jax.lax.fori_loop(0, n, body,
                                 (pv, st, jnp.zeros((), jnp.float32)))

    jitted = jax.jit(multi_step, static_argnums=(4,), donate_argnums=(0, 1))
    st = adamw_init(params)
    rng = np.random.RandomState(0)
    x = rng.randn(batch, 3, hw, hw).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int32)
    params, st, loss = jitted(params, st, x, y, steps)
    _ = float(loss)
    t0 = time.perf_counter()
    params, st, loss = jitted(params, st, x, y, steps)
    lv = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec" if on_tpu
        else "resnet18_train_imgs_per_sec",
        "value": round(batch * steps / dt, 1), "unit": "imgs/s",
        "vs_baseline": 0.0,  # reference publishes no number (BASELINE.md)
        "params": n_params, "platform": platform, "final_loss": lv}))


def moe_bench():
    """BASELINE config 4: MoE expert-parallel dispatch throughput.
    Run: python bench.py moe."""
    import jax
    import jax.numpy as jnp

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    d_model, d_hidden = (1024, 4096) if on_tpu else (32, 64)
    n_expert = 8
    b, s = (8, 1024) if on_tpu else (2, 16)
    experts = [nn.Sequential(nn.Linear(d_model, d_hidden), nn.GELU(),
                             nn.Linear(d_hidden, d_model))
               for _ in range(n_expert)]
    layer = MoELayer(d_model=d_model, experts=experts,
                     gate={"type": "gshard", "top_k": 2})
    x = jax.device_put(
        np.random.RandomState(0).randn(b, s, d_model).astype(np.float32))

    from paddle_tpu.nn.functional_call import functional_call

    params = {k: p.value for k, p in layer.named_parameters()}

    def fwd(pv, xv):
        out = functional_call(layer, pv, paddle.Tensor(xv))
        return jnp.sum((out.value if hasattr(out, "value") else out)
                       .astype(jnp.float32))

    def multi(pv, xv, n):
        # chain iterations through the input (tiny nonzero perturbation)
        # so XLA cannot hoist the loop-invariant forward out of the loop
        def body(_, carry):
            acc, xv = carry
            s = fwd(pv, xv)
            return s, xv + (s * 1e-30).astype(xv.dtype)

        acc, _ = jax.lax.fori_loop(
            0, n, body, (jnp.zeros((), jnp.float32), xv))
        return acc

    jitted = jax.jit(multi, static_argnums=(2,))
    steps = 10 if on_tpu else 2
    _ = float(jitted(params, x, steps))  # compile + warm
    t0 = time.perf_counter()
    _ = float(jitted(params, x, steps))  # one dispatch, readback barrier
    dt = time.perf_counter() - t0
    toks = b * s * steps
    print(json.dumps({
        "metric": "moe_gshard_fwd_tokens_per_sec", "value": round(toks / dt, 1),
        "unit": "tokens/s", "vs_baseline": 0.0, "n_expert": n_expert,
        "platform": platform}))


def vit_bench():
    """BASELINE config 5: ViT-Huge fused-transformer INFERENCE imgs/sec.
    Encoder = patch-embed conv + scan-over-layers pre-LN transformer with
    the framework's flash kernel (non-causal), mean-pool head — the
    fused_multi_transformer inference path at encoder shapes.
    Run: python bench.py vit."""
    import jax
    import jax.numpy as jnp

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    if on_tpu:
        # ViT-H/14 at 224: hidden 1280, 32 layers, 16 heads, mlp 5120;
        # mean-pool (no cls token) keeps 256 tokens — flash-block friendly
        H, L, NH, MLP, P_, IMG, B = 1280, 32, 16, 5120, 14, 224, 32
        dt = jnp.bfloat16
    else:
        H, L, NH, MLP, P_, IMG, B = 64, 2, 4, 128, 16, 64, 2
        dt = jnp.float32
    S = (IMG // P_) ** 2
    hd = H // NH
    rng = np.random.RandomState(0)

    def mk(*s):
        return jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02, dt)

    params = {
        "patch": mk(P_, P_, 3, H), "pos": mk(1, S, H),
        "ln1": jnp.ones((L, H), dt), "qkv": mk(L, H, 3 * H),
        "proj": mk(L, H, H), "ln2": jnp.ones((L, H), dt),
        "fc1": mk(L, H, MLP), "fc2": mk(L, MLP, H),
        "head": mk(H, 1000),
    }

    def ln(x, w):
        xf = x.astype(jnp.float32)
        y = (xf - xf.mean(-1, keepdims=True)) * jax.lax.rsqrt(
            xf.var(-1, keepdims=True) + 1e-6)
        return (y * w.astype(jnp.float32)).astype(x.dtype)

    def encoder_layer(x, lp):
        from paddle_tpu.ops.pallas import flash_attention

        b, s, _ = x.shape
        xn = ln(x, lp["ln1"])
        qkv = (xn @ lp["qkv"]).reshape(b, s, 3, NH, hd)
        ctx = flash_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                              causal=False)
        x = x + ctx.reshape(b, s, H) @ lp["proj"]
        xn = ln(x, lp["ln2"])
        return x + jax.nn.gelu(xn @ lp["fc1"]) @ lp["fc2"]

    def fwd(pv, img):
        x = jax.lax.conv_general_dilated(
            img, pv["patch"], (P_, P_), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = x.reshape(img.shape[0], S, H) + pv["pos"]
        x, _ = jax.lax.scan(
            lambda c, lp: (encoder_layer(c, lp), None), x,
            {k: pv[k] for k in ("ln1", "qkv", "proj", "ln2", "fc1", "fc2")})
        return jnp.mean(x.astype(jnp.float32), axis=1) @ pv[
            "head"].astype(jnp.float32)

    def multi(pv, img, n):
        def body(_, carry):
            acc, img = carry
            out = fwd(pv, img)
            s = jnp.sum(out) * 1e-30
            return acc + jnp.sum(out), img + s.astype(img.dtype)

        acc, _ = jax.lax.fori_loop(0, n, body,
                                   (jnp.zeros((), jnp.float32), img))
        return acc

    jitted = jax.jit(multi, static_argnums=(2,))
    img = jnp.asarray(rng.randn(B, IMG, IMG, 3).astype(np.float32), dt)
    steps = 10 if on_tpu else 2
    _ = float(jitted(params, img, steps))          # compile + warm
    t0 = time.perf_counter()
    _ = float(jitted(params, img, steps))          # one dispatch
    dt_s = time.perf_counter() - t0
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    print(json.dumps({
        "metric": "vit_h_infer_imgs_per_sec" if on_tpu
        else "vit_tiny_infer_imgs_per_sec",
        "value": round(B * steps / dt_s, 1), "unit": "imgs/s",
        "vs_baseline": 0.0,  # reference publishes no number (BASELINE.md)
        "params": n_params, "platform": platform}))


def ragged_bench():
    """Mixed-length decode throughput (VERDICT r3 #6): tokens/s on a
    ragged batch must NOT degrade to the uniform-max-length cost — the
    decode kernel's per-row seq_lens skip S-blocks past each row's length
    (reference serves mixed lengths after remove_padding,
    fused_multi_transformer_op.cu.h:1641). Prints one JSON line comparing
    a batch of all-long rows vs the same batch with mixed lengths."""
    import jax
    import jax.numpy as jnp

    platform = _devices_or_cpu_fallback()[0].platform
    on_tpu = platform == "tpu"

    from paddle_tpu.inference.generation import (ContinuousBatchingEngine,
                                                 GenerationConfig)
    from paddle_tpu.models import LlamaForCausalLM, llama_config

    if on_tpu:
        cfg_m = llama_config("350m", dtype="bfloat16",
                             num_attention_heads=8, num_key_value_heads=8,
                             max_position_embeddings=2048)
        B, max_len, steps = 8, 2048, 64
        long_len = 1792
        mixed = [128, 256, 384, 512, 768, 1024, 1536, 1792]
    else:
        cfg_m = llama_config("tiny")
        B, max_len, steps = 4, 256, 16
        long_len = 192
        mixed = [16, 48, 96, 192]

    model = LlamaForCausalLM(cfg_m)
    model.eval()
    rng = np.random.RandomState(0)
    gcfg = GenerationConfig(max_new_tokens=steps + 1)

    def rate(lens):
        eng = ContinuousBatchingEngine(model, max_batch=B, max_len=max_len)
        for n in lens:
            eng.add_request(
                rng.randint(0, cfg_m.vocab_size, (n,)).astype(np.int32),
                gcfg)
        seg = eng._segment_fn(steps)
        args = (eng.params, eng.last, eng.lens, eng.done_dev,
                eng.active_dev, eng.samp, eng.caches)
        key = jax.random.PRNGKey(0)
        out = seg(*args, key)                      # compile + warm
        _ = float(jnp.sum(out[0]))
        eng.caches = out[4]
        t0 = time.perf_counter()
        out = seg(eng.params, out[1], out[2], out[3], eng.active_dev,
                  eng.samp, eng.caches, key)
        _ = float(jnp.sum(out[0]))
        dt = time.perf_counter() - t0
        return B * steps / dt

    uniform = rate([long_len] * B)
    ragged = rate(mixed)
    print(json.dumps({
        "metric": "ragged_decode_speedup" if on_tpu
        else "ragged_decode_speedup_tiny",
        "value": round(ragged / uniform, 3), "unit": "x vs uniform-long",
        "vs_baseline": round(ragged / uniform, 3),
        "uniform_tok_s": round(uniform, 1),
        "ragged_tok_s": round(ragged, 1),
        "mean_len_ratio": round(sum(mixed) / (long_len * len(mixed)), 3),
        "platform": platform}))


def hybrid_bench():
    """BASELINE config 3 (Llama-2 13B/65B hybrid TP x PP x sharding):
    COMPILE-ONLY per-device memory feasibility at real dims over virtual
    device meshes — the at-scale proof that stage-local PP + ZeRO
    placement fits a v5p HBM budget, with no hardware needed.

    Each config runs in a subprocess (the virtual device count must be
    fixed before jax initializes). Writes MEMORY_CONFIG3.json and prints
    the one-line summary record."""
    import os
    import subprocess

    configs = [
        # (preset, ndev, axes dict, stash, seq, M, budget GiB, zero_stage)
        ("13b", 8, dict(pp=2, mp=2, sharding=2), "input", 4096, 8, 95, 2),
        ("13b", 8, dict(pp=2, mp=2, sharding=2), "residuals", 4096, 8, 95,
         2),
        ("65b", 64, dict(pp=8, mp=4, sharding=2), "input", 4096, 16, 95,
         2),
        ("65b", 64, dict(pp=8, mp=4, sharding=2), "residuals", 4096, 16,
         95, 2),
        # BASELINE config 3 names sharding-stage-3 explicitly
        ("65b", 64, dict(pp=8, mp=4, sharding=2), "residuals", 4096, 16,
         95, 3),
    ]
    runner = r'''
import sys, os, json, time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count="
                           + sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models.llama import llama_config
from paddle_tpu.models.llama_pp import hybrid_memory_analysis

spec = json.loads(sys.argv[1])
cfg = llama_config(spec["preset"])
mesh = build_mesh(**spec["axes"])
set_mesh(mesh)
t0 = time.time()
rep = hybrid_memory_analysis(
    cfg, mesh, accumulate_steps=spec["M"], seq_len=spec["seq"],
    remat=(spec["stash"] == "input"), stash=spec["stash"],
    hbm_budget=spec["budget_gib"] << 30,
    zero_stage=spec.get("zero_stage", 2))
rep["compile_secs"] = round(time.time() - t0, 1)
print("HYBRID_REPORT " + json.dumps(rep))
'''
    reports = []
    for preset, ndev, axes, stash, seq, M, budget, zstage in configs:
        spec = json.dumps({"preset": preset, "axes": axes, "stash": stash,
                           "seq": seq, "M": M, "budget_gib": budget,
                           "zero_stage": zstage})
        try:
            proc = subprocess.run(
                [sys.executable, "-c", runner, spec, str(ndev)],
                capture_output=True, text=True, timeout=1800,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            line = next((ln for ln in proc.stdout.splitlines()
                         if ln.startswith("HYBRID_REPORT ")), None)
            if line:
                reports.append(json.loads(line[len("HYBRID_REPORT "):]))
            else:
                reports.append({
                    "model": preset, "stash": stash, "error":
                    (proc.stderr.strip().splitlines() or ["no output"])
                    [-1][:200]})
        except subprocess.TimeoutExpired:
            reports.append({"model": preset, "stash": stash,
                            "error": "compile timeout 1800s"})
        print(json.dumps({"progress": reports[-1]}), file=sys.stderr)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "MEMORY_CONFIG3.json")
    with open(out, "w") as f:
        json.dump(reports, f, indent=1)
    fits = [r for r in reports if r.get("fits")]
    print(json.dumps({
        "metric": "config3_memory_fits",
        "value": len(fits), "unit": f"of {len(reports)} configs",
        "vs_baseline": len(fits) / max(len(reports), 1),
        "detail": [{ "model": r.get("model"), "stash": r.get("stash"),
                     "peak_gib": r.get("peak_gib"),
                     "fits": r.get("fits", False)} for r in reports]}))


def _watchdog_reexec() -> None:
    """Mid-compile remote-transport hangs (2026-07-31 session: exp_dots
    and the autotune sweep both hung >20min holding the device claim)
    would leave the round with NO record — worse than a CPU one.  Run the
    real bench in a child with a wall-clock budget; if it produces no
    record line, retry once with BENCH_FORCE_CPU=1.  Skipped when already
    CPU-pinned and for the compile-only hybrid mode (internal per-config
    subprocess timeouts, legitimate multi-hour total).

    Budgets: accelerator attempt BENCH_WATCHDOG_SECS (default 1500) +
    CPU retry 600 = 2100s worst case, under the session runbook's default
    2400s step timeout (experiments/tpu_session.sh raises both for
    cold-cache modes).  A cold remote compile CAN legitimately exceed the
    default — the in-repo .jax_cache keeps the flagship modes warm, and
    callers with slow-but-healthy tunnels should raise
    BENCH_WATCHDOG_SECS rather than lose a real TPU record to the
    CPU fallback.  The child runs in its own process group, killed as a
    group on timeout OR when this wrapper is SIGTERMed (the runbook's
    outer `timeout`), so a hung bench can never orphan-hold the device
    claim."""
    import os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "experiments"))
    from _budget import run_budgeted

    env = dict(os.environ, BENCH_INNER="1")
    budgets = {"accelerator": int(os.environ.get("BENCH_WATCHDOG_SECS",
                                                 "1500")),
               "cpu": 600}
    for attempt, budget in budgets.items():
        if attempt == "cpu":
            env["BENCH_FORCE_CPU"] = "1"
        # -u: the child writes to a pipe (block-buffered by default) — a
        # record printed just before a teardown hang must survive the
        # group kill
        r = run_budgeted(
            [sys.executable, "-u", os.path.abspath(__file__)]
            + sys.argv[1:], budget, env=env)
        sys.stderr.write(r.err[-20000:])
        line = next((ln for ln in r.out.splitlines()
                     if '"metric"' in ln), None)
        if line:
            print(line)
            raise SystemExit(0)
        why = (f"hung >{budget}s (group killed)" if r.timed_out
               else f"exited rc={r.returncode} with no record")
        print(json.dumps({"warning": f"bench {attempt} attempt {why}",
                          "partial_stdout_tail": r.out[-500:]}),
              file=sys.stderr)
    raise SystemExit(1)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "train"
    import os as _os
    if (mode != "hybrid" and _os.environ.get("BENCH_INNER") != "1"
            and _os.environ.get("JAX_PLATFORMS", "") != "cpu"):
        _watchdog_reexec()
    if mode == "decode":
        decode_bench()
    elif mode == "spec":
        spec_bench()
    elif mode == "resnet":
        resnet_bench()
    elif mode == "moe":
        moe_bench()
    elif mode == "vit":
        vit_bench()
    elif mode == "hybrid":
        hybrid_bench()
    elif mode == "ragged":
        ragged_bench()
    elif mode == "train":
        main(sys.argv[2] if len(sys.argv) > 2 else "350m")
    elif mode == "1.3b":
        main("1.3b")
    else:
        raise SystemExit(
            f"unknown bench mode {mode!r} "
            "(train|decode|spec|resnet|moe|vit|1.3b|hybrid|ragged)")
