"""Benchmark driver: one JSON line for the round record.

Measures flagship-model (Llama-family) training throughput on the available
chip: tokens/sec/chip and MFU (model FLOPs 6·N·tokens / peak). North star
(BASELINE.md): ≥50% MFU — `vs_baseline` reports MFU/0.50 so 1.0 == target
(the reference publishes no absolute numbers, BASELINE.json "published": {}).

Run: python bench.py            (real TPU under axon; CPU fallback = tiny cfg)
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import os

    import jax

    # persistent compile cache: bench iterations recompile a ~20-min XLA
    # program otherwise (remote-compile helper has no cross-run cache)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.nn.functional_call import functional_call
    from paddle_tpu.optimizer.functional import (adamw_init, adamw_update,
                                                 clip_by_global_norm)

    if on_tpu:
        # 350M-param Llama with head_dim 128 (8 heads x 128 instead of
        # 16 x 64): same parameter count, full-width MXU lanes on the
        # attention contractions. Full activation recompute bounds live
        # activations to one layer's worth (round-1 bench OOMed without it).
        cfg = llama_config("350m", dtype="bfloat16",
                           num_attention_heads=8, num_key_value_heads=8,
                           max_position_embeddings=2048, recompute="full")
        batch, seq, steps = 8, 2048, 10
        kind = jax.devices()[0].device_kind.lower()
        if "lite" in kind or "v5e" in kind:
            peak = 394e12  # v5e bf16
        elif "v5" in kind:
            peak = 459e12  # v5p bf16
        else:
            peak = 275e12  # v4
    else:
        cfg = llama_config("tiny")
        batch, seq, steps = 4, 128, 3
        peak = 1e12  # meaningless on CPU; MFU reported but not comparable

    model = LlamaForCausalLM(cfg)
    # keep training=True so cfg.recompute applies; the model has no dropout,
    # so train/eval forward math is identical
    params = {k: p.value for k, p in model.named_parameters()}
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    opt_state = adamw_init(params)

    def loss_fn(pv, ids, labels):
        return functional_call(model, pv, paddle.Tensor(ids),
                               paddle.Tensor(labels))

    def train_step(pv, st, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(pv, ids, labels)
        grads, _ = clip_by_global_norm(grads, 1.0)
        st, pv = adamw_update(grads, st, pv, lr=1e-4)
        return pv, st, loss

    # ONE dispatch for the whole timed loop (lax.fori_loop inside jit): the
    # remote-tunnel dispatch latency would otherwise dominate, and
    # block_until_ready is not an honest barrier through the tunnel — a
    # scalar host readback is.
    def multi_step(pv, st, ids, labels, n):
        import jax.numpy as jnp

        def body(_, carry):
            pv, st, _ = carry
            pv, st, loss = train_step(pv, st, ids, labels)
            return pv, st, loss.astype(jnp.float32)

        return jax.lax.fori_loop(0, n, body,
                                 (pv, st, jnp.zeros((), jnp.float32)))

    jitted = jax.jit(multi_step, static_argnums=(4,), donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)

    # warmup / compile with the SAME static n as the timed call
    params, opt_state, loss = jitted(params, opt_state, ids, labels, steps)
    _ = float(loss)  # host readback barrier

    t0 = time.perf_counter()
    params, opt_state, loss = jitted(params, opt_state, ids, labels, steps)
    loss_val = float(loss)  # host readback barrier
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tps = tokens / dt
    model_flops = 6.0 * n_params * tokens  # fwd+bwd ≈ 6·N per token
    mfu = model_flops / dt / peak
    rec = {
        "metric": f"llama_{'350m' if on_tpu else 'tiny'}_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "params": n_params,
        "platform": platform,
        "final_loss": loss_val,
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
