"""Lint engine shared by every checker: module parsing (AST + parent
links + comment annotations), findings, drift-stable fingerprints, and
the triage baseline.

Design notes:

- Annotations live in COMMENTS so they cost nothing at runtime. A
  directive applies to its own line, and a directive on a comment-only
  line also applies to the next code line (so a comment block above a
  statement annotates the statement).
- Fingerprints deliberately EXCLUDE line numbers: a baseline must
  survive unrelated edits above a finding. Identity is
  ``checker|file|enclosing-qualname|detail|occurrence`` where
  ``detail`` is a short stable token (the synced call, the metric name,
  the guarded attribute) and ``occurrence`` disambiguates repeats of
  the same token inside one scope (ordered by line).
- The baseline is "no NEW violations": every entry carries a required
  human justification, and a finding matching an entry is suppressed.
  Stale entries (nothing matches them anymore) are reported so the
  baseline shrinks over time instead of fossilizing.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: directive spelling: ``# lint: name`` or ``# lint: name(argument)``.
#: A reason may run to end-of-line without its closing paren (comment
#: blocks wrap) — the first line must still carry real words.
_DIRECTIVE_RE = re.compile(
    r"#\s*lint:\s*([a-z][a-z0-9-]*)\s*(?:\(([^)]*)\)?)?")
#: field-guard spelling: ``# guarded-by: self._lock`` (or a thread name)
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([^#]+?)\s*$")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")

KNOWN_DIRECTIVES = frozenset({
    "hot-path",            # PT002 root: scan this function (transitively)
    "allow-host-sync",     # PT002 escape; reason required
    "allow-blocking-io",   # PT006 escape; reason required
    "allow-recompile",     # PT001 escape; reason required
    "allow-unlocked",      # PT004 escape; reason required
    "allow-ungated",       # PT005 escape; reason required
    "allow-series",        # PT003 escape; reason required
    "retires-series",      # PT003: treat this method as a retirement root
})


@dataclass
class Finding:
    """One checker hit. ``detail`` and ``context`` feed the
    drift-stable fingerprint; ``line`` is for humans and editors."""

    checker: str
    file: str          # repo-relative posix path
    line: int
    message: str
    hint: str = ""
    context: str = ""  # enclosing qualname ("Server._gap", "<module>")
    detail: str = ""   # stable token ("np.asarray", metric name, attr)
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        return "|".join((self.checker, self.file, self.context,
                         self.detail, str(self.occurrence)))

    def render(self) -> str:
        out = f"{self.file}:{self.line}: {self.checker} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        out += f"\n    fingerprint: {self.fingerprint}"
        return out


class Annotations:
    """Comment-directive index for one source file."""

    def __init__(self, lines: Sequence[str]):
        self._by_line: Dict[int, List[Tuple[str, Optional[str]]]] = {}
        self._guards: Dict[int, str] = {}
        # one record per PHYSICAL directive (for unknown-name
        # reporting) — _by_line may alias the same directive onto the
        # code line it annotates
        self._raw: List[Tuple[int, str]] = []
        pending: List[Tuple[str, Optional[str]]] = []
        pending_guard: Optional[str] = None
        for i, text in enumerate(lines, start=1):
            own: List[Tuple[str, Optional[str]]] = []
            for m in _DIRECTIVE_RE.finditer(text):
                own.append((m.group(1), m.group(2)))
                self._raw.append((i, m.group(1)))
            gm = _GUARDED_RE.search(text)
            if _COMMENT_ONLY_RE.match(text):
                # comment-only line: directives carry forward to the
                # next code line (plus apply to this line itself)
                pending.extend(own)
                if gm:
                    pending_guard = gm.group(1).strip()
                if own:
                    self._by_line[i] = list(own)
                continue
            if not text.strip():
                # a BLANK line breaks the pending block: an orphaned
                # comment (its statement deleted) must not silently
                # attach its escape to whatever code comes next
                pending = []
                pending_guard = None
                continue
            eff = pending + own
            if eff:
                self._by_line[i] = eff
            guard = (gm.group(1).strip() if gm else pending_guard)
            if guard:
                self._guards[i] = guard
            pending = []
            pending_guard = None

    def on_line(self, lineno: int, name: str) -> Optional[Tuple[str, str]]:
        """``(name, arg-or-'')`` when directive ``name`` applies to
        ``lineno``, else None."""
        for d, arg in self._by_line.get(lineno, ()):
            if d == name:
                return (d, (arg or "").strip())
        return None

    def guard_on_line(self, lineno: int) -> Optional[str]:
        return self._guards.get(lineno)

    def unknown_directives(self) -> List[Tuple[int, str]]:
        return [(line, d) for line, d in self._raw
                if d not in KNOWN_DIRECTIVES]


class Module:
    """One parsed source file: AST with parent links, comment
    annotations, scope helpers. Checkers receive this."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.ann = Annotations(self.lines)
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

    def directive_for(self, node: ast.AST, name: str
                      ) -> Optional[Tuple[str, str]]:
        """Directive applying to ``node``: on its own line, or on (or
        above) the first line of its enclosing STATEMENT — so an escape
        above a multi-line statement covers every expression in it."""
        hit = self.ann.on_line(getattr(node, "lineno", 0), name)
        if hit is not None:
            return hit
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parent.get(cur)
        if cur is not None and cur.lineno != getattr(node, "lineno", 0):
            return self.ann.on_line(cur.lineno, name)
        return None

    # -- scope helpers -------------------------------------------------------
    def ancestors(self, node: ast.AST) -> List[ast.AST]:
        out = []
        cur = self.parent.get(node)
        while cur is not None:
            out.append(cur)
            cur = self.parent.get(cur)
        return out

    def enclosing_function(self, node: ast.AST):
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_class(self, node: ast.AST):
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

    def qualname(self, node: ast.AST) -> str:
        parts = []
        for a in [node] + self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
        return ".".join(reversed(parts)) or "<module>"

    def scope_qualname(self, node: ast.AST) -> str:
        """Qualname of the scope CONTAINING ``node`` (not node itself
        even when node is a def)."""
        parts = []
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
        return ".".join(reversed(parts)) or "<module>"


def class_chain(cls: ast.ClassDef,
                by_name: Dict[str, "ast.ClassDef"]) -> List[ast.ClassDef]:
    """``cls`` plus every base class resolvable BY NAME within the same
    module (``by_name``: class name -> ClassDef), subclass first — the
    shared MRO approximation PT002's method resolution and PT003's
    retirement-root search both walk."""
    out, seen, todo = [], set(), [cls]
    while todo:
        c = todo.pop()
        if c.name in seen:
            continue
        seen.add(c.name)
        out.append(c)
        for b in c.bases:
            n = dotted_name(b)
            if n and n.split(".")[-1] in by_name:
                todo.append(by_name[n.split(".")[-1]])
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- collection / running ----------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for base, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(base, f))
    return out


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def lint_module(mod: Module, checks: Optional[Sequence[str]] = None
                ) -> List[Finding]:
    from .checks import CHECKERS

    findings: List[Finding] = []
    for cid, fn in CHECKERS.items():
        if checks is not None and cid not in checks:
            continue
        findings.extend(fn(mod))
    # unknown ``# lint:`` directives are config errors: a typo'd escape
    # hatch must not silently stop suppressing
    for line, d in mod.ann.unknown_directives():
        findings.append(Finding(
            checker="PT000", file=mod.rel, line=line,
            message=f"unknown lint directive {d!r}",
            hint="known: " + ", ".join(sorted(KNOWN_DIRECTIVES)),
            context="<directives>", detail=d))
    findings.sort(key=lambda f: (f.file, f.line, f.checker, f.detail))
    return findings


def lint_source(source: str, filename: str = "<fixture>.py",
                checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one in-memory source blob (the unit-test surface)."""
    mod = Module(filename, source)
    return fingerprint_findings(lint_module(mod, checks))


def covered_relfiles(paths: Sequence[str],
                     root: Optional[str] = None) -> set:
    """Repo-relative paths a ``lint_paths`` run over ``paths`` examines
    — the scope bound for baseline staleness/regeneration."""
    root = os.path.abspath(root or os.getcwd())
    return {_relpath(p, root) for p in iter_py_files(paths)}


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               checks: Optional[Sequence[str]] = None) -> List[Finding]:
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        rel = _relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            mod = Module(rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                checker="PT000", file=rel, line=e.lineno or 0,
                message=f"syntax error: {e.msg}",
                context="<parse>", detail="syntax-error"))
            continue
        findings.extend(lint_module(mod, checks))
    findings.sort(key=lambda f: (f.file, f.line, f.checker, f.detail))
    return fingerprint_findings(findings)


def fingerprint_findings(findings: List[Finding]) -> List[Finding]:
    """Assign occurrence indices so identical (checker, file, context,
    detail) repeats stay distinguishable, ordered by line."""
    seen: Dict[Tuple[str, str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.file, f.line)):
        key = (f.checker, f.file, f.context, f.detail)
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1
    return findings


# -- baseline ----------------------------------------------------------------

BASELINE_VERSION = 1


class BaselineError(ValueError):
    """The baseline file is malformed (bad JSON, missing or empty
    justification) — a hard error, not a suppression."""


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry. Every entry must carry a non-empty
    ``justification`` — a suppression without a written reason is the
    reviewer-vigilance regime this tool replaces."""
    with open(path, "r", encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: invalid JSON: {e}") from e
    if not isinstance(data, dict) or "entries" not in data:
        raise BaselineError(f"{path}: expected {{'entries': [...]}}")
    out: Dict[str, dict] = {}
    for i, entry in enumerate(data["entries"]):
        fp = entry.get("fingerprint")
        if not fp:
            raise BaselineError(f"{path}: entries[{i}] has no fingerprint")
        just = (entry.get("justification") or "").strip()
        if not just:
            raise BaselineError(
                f"{path}: entries[{i}] ({fp}) has no justification — "
                "every baselined finding needs a written reason")
        if fp in out:
            raise BaselineError(f"{path}: duplicate fingerprint {fp}")
        out[fp] = entry
    return out


def _entry_scope(fp: str, entry: dict) -> Tuple[str, str]:
    """(checker, file) of a baseline entry — from its fields when
    present, else parsed out of the fingerprint."""
    parts = fp.split("|")
    checker = entry.get("checker") or (parts[0] if parts else "")
    file = entry.get("file") or (parts[1] if len(parts) > 1 else "")
    return checker, file


def apply_baseline(findings: List[Finding], baseline: Dict[str, dict],
                   covered_files: Optional[set] = None,
                   covered_checks: Optional[Sequence[str]] = None
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (unbaselined, suppressed, stale_fingerprints).

    ``covered_files``/``covered_checks`` bound what this RUN looked at:
    an entry outside the scope (a subtree run, a ``--checks`` subset)
    is neither matched nor STALE — only a run that actually re-linted
    an entry's file with its checker may declare it gone."""
    new, suppressed = [], []
    matched = set()
    for f in findings:
        fp = f.fingerprint
        if fp in baseline:
            suppressed.append(f)
            matched.add(fp)
        else:
            new.append(f)
    stale = []
    for fp, entry in baseline.items():
        if fp in matched:
            continue
        checker, file = _entry_scope(fp, entry)
        if covered_files is not None and file not in covered_files:
            continue
        if covered_checks is not None and checker not in covered_checks:
            continue
        stale.append(fp)
    return new, suppressed, sorted(stale)


def generate_baseline(findings: List[Finding],
                      previous: Optional[Dict[str, dict]] = None,
                      covered_files: Optional[set] = None,
                      covered_checks: Optional[Sequence[str]] = None
                      ) -> dict:
    """Baseline document for the current findings, carrying forward the
    justifications of entries that still match; new entries get an
    UNREVIEWED placeholder that ``load_baseline`` will accept only once
    a human replaces it (it is non-empty on purpose: ``--fix-baseline``
    must produce a loadable file whose unreviewed entries are
    grep-able).

    Previous entries OUTSIDE this run's scope (``covered_files`` /
    ``covered_checks``) are kept verbatim: a subtree or ``--checks``
    regeneration must never delete suppressions — and their written
    justifications — it never re-examined."""
    previous = previous or {}
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: f.fingerprint):
        fp = f.fingerprint
        prev = previous.get(fp)
        seen.add(fp)
        entries.append({
            "fingerprint": fp,
            "checker": f.checker,
            "file": f.file,
            "context": f.context,
            "detail": f.detail,
            "message": f.message,
            "justification": (prev.get("justification")
                              if prev else
                              "UNREVIEWED — replace with a real "
                              "justification before committing"),
        })
    for fp, entry in previous.items():
        if fp in seen:
            continue
        checker, file = _entry_scope(fp, entry)
        out_of_scope = (
            (covered_files is not None and file not in covered_files)
            or (covered_checks is not None
                and checker not in covered_checks))
        if out_of_scope:
            entries.append(dict(entry))
    entries.sort(key=lambda e: e["fingerprint"])
    return {
        "version": BASELINE_VERSION,
        "note": ("Triaged pre-existing findings; the CI bar is zero "
                 "UNBASELINED findings. Remove entries as the code "
                 "they suppress is fixed — stale entries are reported."),
        "entries": entries,
    }


def write_baseline(doc: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
