"""CLI: ``python -m tools.lint paddle_tpu/ [options]``.

Exit status 0 iff zero UNBASELINED findings (the CI bar). Common runs::

    python -m tools.lint paddle_tpu/                 # the gate
    python -m tools.lint paddle_tpu/ --summary       # per-checker table
    python -m tools.lint paddle_tpu/serving/         # one subtree
    python -m tools.lint paddle_tpu/ --fix-baseline  # re-triage: rewrite
        # baseline.json keeping justifications of surviving entries;
        # NEW entries get an UNREVIEWED placeholder you must replace
    python -m tools.lint paddle_tpu/ --no-baseline   # everything, raw
"""
from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

from .core import (BaselineError, apply_baseline, covered_relfiles,
                   default_baseline_path, generate_baseline, lint_paths,
                   load_baseline, write_baseline)
from .checks import CHECKERS


def _summary(findings, suppressed, stale, top: int = 8) -> str:
    lines = ["paddle_tpu-lint summary", "=" * 23, "",
             f"{'checker':<8} {'new':>5} {'baselined':>10}"]
    new_c = Counter(f.checker for f in findings)
    sup_c = Counter(f.checker for f in suppressed)
    for cid in sorted(set(CHECKERS) | set(new_c) | set(sup_c)):
        lines.append(f"{cid:<8} {new_c.get(cid, 0):>5} "
                     f"{sup_c.get(cid, 0):>10}")
    lines.append(f"{'total':<8} {sum(new_c.values()):>5} "
                 f"{sum(sup_c.values()):>10}")
    files = Counter(f.file for f in findings)
    if files:
        lines += ["", f"top files (new findings):"]
        for path, n in files.most_common(top):
            lines.append(f"  {n:>4}  {path}")
    if stale:
        lines += ["", f"stale baseline entries (nothing matches them "
                      f"anymore — prune with --fix-baseline): "
                      f"{len(stale)}"]
        for fp in stale[:top]:
            lines.append(f"  {fp}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="invariant-aware static analysis for paddle_tpu "
                    "(PT001 recompile / PT002 host-sync / PT003 series "
                    "lifecycle / PT004 lock discipline / PT005 flag "
                    "gating)")
    ap.add_argument("paths", nargs="+", help="files/dirs to lint")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/lint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppressing nothing")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite the baseline from the CURRENT "
                         "findings, keeping justifications of entries "
                         "that still match; new entries get an "
                         "UNREVIEWED placeholder to replace")
    ap.add_argument("--summary", action="store_true",
                    help="per-checker counts + top files "
                         "(monitor_report-style)")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset, e.g. PT001,PT003")
    args = ap.parse_args(argv)

    checks = (None if args.checks is None
              else [c.strip().upper() for c in args.checks.split(",")])
    if checks is not None:
        unknown = [c for c in checks if c not in CHECKERS]
        if unknown:
            print(f"unknown checker id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(CHECKERS))})",
                  file=sys.stderr)
            return 2

    findings = lint_paths(args.paths, root=os.getcwd(), checks=checks)
    covered = covered_relfiles(args.paths, root=os.getcwd())

    baseline_path = args.baseline or default_baseline_path()
    baseline = {}
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except BaselineError as e:
            print(f"baseline error: {e}", file=sys.stderr)
            return 2

    if args.fix_baseline:
        # regeneration always starts from the ON-DISK baseline (even
        # under --no-baseline) and keeps entries outside this run's
        # scope: a subtree or --checks regeneration must not delete
        # suppressions — or their justifications — it never re-examined
        previous = baseline
        if not previous and os.path.exists(baseline_path):
            try:
                previous = load_baseline(baseline_path)
            except BaselineError as e:
                print(f"baseline error: {e}", file=sys.stderr)
                return 2
        doc = generate_baseline(findings, previous=previous,
                                covered_files=covered,
                                covered_checks=checks)
        write_baseline(doc, baseline_path)
        unreviewed = sum(
            1 for e in doc["entries"]
            if e["justification"].startswith("UNREVIEWED"))
        print(f"wrote {baseline_path}: {len(doc['entries'])} entries "
              f"({unreviewed} UNREVIEWED — replace the placeholders "
              "before committing)")
        return 0

    new, suppressed, stale = apply_baseline(
        findings, baseline, covered_files=covered,
        covered_checks=checks)

    if args.summary:
        print(_summary(new, suppressed, stale))
        if new:
            print()
    for f in new:
        print(f.render())
    if not args.summary:
        if suppressed:
            print(f"[{len(suppressed)} baselined finding(s) suppressed "
                  f"by {os.path.relpath(baseline_path)}]")
        if stale:
            print(f"[{len(stale)} stale baseline entrie(s) — prune "
                  "with --fix-baseline]")
    if new:
        print(f"\n{len(new)} unbaselined finding(s). The bar is zero: "
              "fix them, annotate the blessed idiom, or triage into "
              "the baseline WITH a justification (--fix-baseline "
              "writes the skeleton).", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
