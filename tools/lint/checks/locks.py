"""PT004 — lock discipline for ``# guarded-by:`` fields (the threaded
serving classes, PR 2/4/9).

Declaration grammar (on or above the field's ``__init__`` assignment)::

    self._flight_dumps = []        # guarded-by: self._lock
    # guarded-by: self._lock
    self._fault_counts = {}
    self._free = []                # guarded-by: scheduler-thread

Two forms:

- ``self.<lock>`` — ENFORCED: every access of the field outside
  ``__init__`` must sit lexically inside a ``with self.<lock>`` (or
  ``with self.<lock>:``-containing multi-item with) in the same
  function. Deliberate lock-free reads (an atomic snapshot of one int/
  ref) carry ``# lint: allow-unlocked(<reason>)`` — the reason is the
  review artifact.
- anything else (e.g. ``scheduler-thread``) — DOCUMENTED ownership,
  not statically enforceable: the checker validates the declaration
  parses and otherwise stays quiet. It still fails a ``self.<lock>``
  declaration whose lock attribute the class never creates (a typo'd
  guard would otherwise enforce nothing).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..core import Finding, Module

_SELF_LOCK_PREFIX = "self."


def _self_attr(node: ast.AST) -> str:
    """'self.x' for Attribute(self, x), else ''."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return ""


def _declared_guards(mod: Module, cls: ast.ClassDef
                     ) -> Dict[str, Tuple[str, int]]:
    """attr name -> (guard expression text, declaration line)."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        guard = mod.ann.guard_on_line(node.lineno)
        if guard is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = _self_attr(t)
            if attr:
                out[attr.split(".", 1)[1]] = (guard, node.lineno)
    return out


def _lock_attrs(cls: ast.ClassDef) -> set:
    """Attributes assigned anywhere in the class body ('self.x' names)
    — used to validate that a declared lock exists."""
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                a = _self_attr(t)
                if a:
                    out.add(a)
    return out


def _within_lock(mod: Module, node: ast.AST, fn: ast.AST,
                 lock_text: str) -> bool:
    for a in mod.ancestors(node):
        if a is fn:
            break
        if isinstance(a, ast.With):
            for item in a.items:
                try:
                    if ast.unparse(item.context_expr) == lock_text:
                        return True
                except Exception:
                    continue
    return False


def check_lock_discipline(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    for cls in [n for n in ast.walk(mod.tree)
                if isinstance(n, ast.ClassDef)]:
        guards = _declared_guards(mod, cls)
        if not guards:
            continue
        attrs = _lock_attrs(cls)
        enforced: Dict[str, str] = {}
        for attr, (guard, decl_line) in guards.items():
            if not guard.startswith(_SELF_LOCK_PREFIX):
                continue    # documented thread-ownership form
            if guard not in attrs:
                findings.append(Finding(
                    checker="PT004", file=mod.rel, line=decl_line,
                    message=f"field {attr!r} declared guarded-by "
                            f"{guard!r}, but {cls.name} never creates "
                            f"{guard} — the guard enforces nothing",
                    hint="fix the lock name in the annotation or "
                         "create the lock in __init__",
                    context=f"{cls.name}.{attr}", detail=f"decl:{attr}"))
                continue
            enforced[attr] = guard
        if not enforced:
            continue
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if m.name == "__init__":
                continue    # construction precedes any second thread
            for node in ast.walk(m):
                attr = _self_attr(node)
                if not attr:
                    continue
                name = attr.split(".", 1)[1]
                lock = enforced.get(name)
                if lock is None:
                    continue
                if _within_lock(mod, node, m, lock):
                    continue
                esc = mod.directive_for(node, "allow-unlocked")
                extra = ""
                if esc is not None:
                    if esc[1]:
                        continue
                    extra = (" [allow-unlocked present but a REASON "
                             "is required]")
                kind = ("write" if isinstance(
                    getattr(node, "ctx", None),
                    (ast.Store, ast.Del)) else "read")
                findings.append(Finding(
                    checker="PT004", file=mod.rel, line=node.lineno,
                    message=f"{kind} of {attr} (guarded-by {lock}) "
                            f"outside `with {lock}` in "
                            f"{cls.name}.{m.name}(){extra}",
                    hint=f"wrap in `with {lock}:` or justify the "
                         "lock-free access: "
                         "# lint: allow-unlocked(<reason>)",
                    context=f"{cls.name}.{m.name}", detail=name))
    return findings
