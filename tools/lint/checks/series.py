"""PT003 — instance-labeled monitor series must be retired (PR 8's
leak class, moved to compile time).

A Counter/Gauge/Histogram created with an INSTANCE label (``server``,
``engine``, ``pool``, ``router``, ``loader``, ``fit`` — the
``monitor.instance_label`` families) exports forever unless its owner
retires it: a dropped engine's gauges keep their last values and label
cardinality grows per instance. PR 8's TestSeriesRetirement caught 3
real leaks at runtime; this checker demands the retirement STATICALLY:

- the creating class must have a retirement root — a method named
  ``close`` / ``shutdown`` / ``__del__`` / ``__exit__`` / ``stop`` /
  ``_retire*``, or any method annotated ``# lint: retires-series`` —
  from which (following intra-class ``self.`` calls) the metric is
  retired;
- "retired" means the metric NAME appears in a retirement-reachable
  body (the ``for name in (...): monitor.remove_series(name, ...)``
  idiom), or a helper whose body creates that metric is invoked there
  as ``self._helper().remove(...)`` / ``monitor.remove_series`` with
  the name resolved through the helper.

Escape hatch (reason required): ``# lint: allow-series(<reason>)`` on
the creation line — for series whose lifecycle genuinely is the
process (e.g. the one process-wide op-latency histogram).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, Module, class_chain, dotted_name

INSTANCE_LABELS = frozenset({
    "server", "engine", "pool", "router", "loader", "fit", "replica"})
_CTORS = {"counter", "gauge", "histogram"}
_CTOR_PREFIXES = {"monitor", "mon", "_monitor", "monitoring"}
_RETIRE_ROOTS = {"close", "shutdown", "__del__", "__exit__", "stop"}


def _is_ctor(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] not in _CTORS:
        return None
    if len(parts) > 1 and parts[0] not in _CTOR_PREFIXES:
        return None
    return parts[-1]


def _literal_strings(node: ast.AST) -> List[str]:
    return [c.value for c in ast.walk(node)
            if isinstance(c, ast.Constant) and isinstance(c.value, str)]


def _labelnames(call: ast.Call) -> List[str]:
    arg = None
    if len(call.args) >= 3:
        arg = call.args[2]
    for kw in call.keywords:
        if kw.arg == "labelnames":
            arg = kw.value
    if arg is None:
        return []
    if isinstance(arg, (ast.Tuple, ast.List)):
        return [e.value for e in arg.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _metric_name(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _retirement_bodies(mod: Module, cls: ast.ClassDef,
                       by_name: Dict[str, ast.ClassDef]) -> List[ast.AST]:
    """Retirement roots of ``cls`` (searching base classes defined in
    the same module too) expanded through intra-class self-calls."""
    chain = class_chain(cls, by_name)
    methods: Dict[str, ast.FunctionDef] = {}
    for c in reversed(chain):           # subclass overrides win
        methods.update(_class_methods(c))
    roots = [m for name, m in methods.items()
             if name in _RETIRE_ROOTS or name.startswith("_retire")
             or mod.ann.on_line(m.lineno, "retires-series") is not None]
    out, visited = [], set()
    while roots:
        m = roots.pop()
        if id(m) in visited:
            continue
        visited.add(id(m))
        out.append(m)
        for node in ast.walk(m):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("self", "cls")):
                target = methods.get(node.func.attr)
                if target is not None:
                    roots.append(target)
    return out


def _retired_names(mod: Module, bodies: List[ast.AST],
                   helper_metrics: Dict[str, Set[str]]) -> Set[str]:
    """Every metric name the retirement bodies reach: literal strings
    anywhere in them (the name-tuple + remove_series idiom) plus the
    metrics of ``self._helper()`` calls appearing there (the
    ``self._gauge().remove(...)`` idiom)."""
    names: Set[str] = set()
    for body in bodies:
        names.update(_literal_strings(body))
        for node in ast.walk(body):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("self", "cls")):
                names.update(helper_metrics.get(node.func.attr, ()))
    return names


def check_series_lifecycle(mod: Module) -> List[Finding]:
    if "/monitor/" in "/" + mod.rel or mod.rel.endswith("monitor.py"):
        return []   # the registry itself is not an instrument owner
    findings: List[Finding] = []
    by_name = {n.name: n for n in mod.tree.body
               if isinstance(n, ast.ClassDef)}

    # helper-name -> metric names created inside it (per class)
    helper_metrics: Dict[str, Dict[str, Set[str]]] = {}
    for cls in by_name.values():
        table: Dict[str, Set[str]] = {}
        for m in _class_methods(cls).values():
            created = set()
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and _is_ctor(node):
                    name = _metric_name(node)
                    if name:
                        created.add(name)
            if created:
                table[m.name] = created
        helper_metrics[cls.name] = table

    retired_cache: Dict[str, Set[str]] = {}

    def retired_for(cls: ast.ClassDef) -> Set[str]:
        if cls.name not in retired_cache:
            bodies = _retirement_bodies(mod, cls, by_name)
            # a ``self._helper().remove(...)`` may resolve through any
            # class in the base chain; merging every class's helper
            # table over-approximates harmlessly (names are unique)
            helpers: Dict[str, Set[str]] = {}
            for table in helper_metrics.values():
                for k, v in table.items():
                    helpers.setdefault(k, set()).update(v)
            retired_cache[cls.name] = _retired_names(mod, bodies, helpers)
        return retired_cache[cls.name]

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _is_ctor(node):
            continue
        labels = set(_labelnames(node)) & INSTANCE_LABELS
        if not labels:
            continue
        name = _metric_name(node)
        if name is None:
            continue
        esc = mod.directive_for(node, "allow-series")
        label_s = "/".join(sorted(labels))
        cls = mod.enclosing_class(node)
        if esc is not None and esc[1]:
            continue
        bad_esc = (" [allow-series present but a REASON is required]"
                   if esc is not None else "")
        if cls is None:
            findings.append(Finding(
                checker="PT003", file=mod.rel, line=node.lineno,
                message=f"series {name!r} carries instance label(s) "
                        f"{label_s} but is created outside a class — "
                        f"no owner can retire it{bad_esc}",
                hint="create it through an owning class with a "
                     "close/shutdown retirement, or justify with "
                     "# lint: allow-series(<reason>)",
                context=mod.scope_qualname(node), detail=name))
            continue
        if name in retired_for(cls) and not bad_esc:
            continue
        findings.append(Finding(
            checker="PT003", file=mod.rel, line=node.lineno,
            message=f"instance-labeled series {name!r} ({label_s}) is "
                    f"never retired by {cls.name}'s close/shutdown — "
                    f"it exports forever after the instance "
                    f"drops{bad_esc}",
            hint=f"add monitor.remove_series({name!r}, "
                 f"{sorted(labels)[0]}=...) to {cls.name}.close/"
                 "shutdown (or a # lint: retires-series method), or "
                 "justify with # lint: allow-series(<reason>)",
            context=mod.scope_qualname(node), detail=name))
    return findings
