"""PT006 — blocking socket I/O reached from an annotated hot path
(the cached-snapshot-only bar the cross-process fleet rides on,
PR 17).

The Router picks replicas UNDER ITS LOCK by reading each replica's
``status`` / ``load()`` / queue-depth surface; for an in-process
Server those are lock-light host reads, and :class:`RemoteReplica`
keeps the contract by serving them from a poller-maintained CACHED
snapshot. A network round-trip smuggled into one of those seams stalls
every routing decision behind a peer's TCP stack — seconds, not the
microseconds the never-block-the-gap bar budgets. Ground truth is the
same ``# lint: hot-path`` annotation PT002 walks (transitively,
intra-module).

Flagged operations inside a hot function:

- ``urllib.request.urlopen(...)`` without a ``timeout=`` kwarg (or
  with an explicit ``timeout=None``) — blocks forever on a dead peer;
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)`` /
  ``socket.create_connection(...)`` without a bounded ``timeout=`` —
  every later request on the connection inherits the block;
- ``.recv()`` / ``.recvfrom()`` / ``.accept()`` / ``.getresponse()``
  — raw socket reads. These are flagged even when a ``settimeout``
  happened earlier (the lint can't see across statements): the
  reviewer writes the one-line reason, same policy as PT002's
  ``np.asarray``.

A bounded ``timeout=`` argument (any expression that is not the
constant ``None``) quiets the constructor/urlopen forms — the checker
enforces that the bound EXISTS, not its value.

Escape hatch (reason REQUIRED): ``# lint: allow-blocking-io(<reason>)``
on or above the flagged line — e.g. a reader thread whose whole job is
to sit in ``getresponse()`` for the stream's lifetime.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Module, dotted_name
from .host_sync import hot_functions

#: constructor/opener forms where a ``timeout=`` kwarg is the fix
_TIMEOUT_CALLS = {
    "urlopen", "urllib.request.urlopen", "request.urlopen",
    "HTTPConnection", "HTTPSConnection",
    "http.client.HTTPConnection", "http.client.HTTPSConnection",
    "client.HTTPConnection", "client.HTTPSConnection",
    "socket.create_connection", "create_connection",
}
#: receive-side methods that block until the peer talks; no per-call
#: timeout exists, so these always need the escape hatch in hot code
_RECV_METHODS = {"recv", "recvfrom", "recv_into", "accept",
                 "getresponse"}


def _has_bounded_timeout(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


def check_socket_io(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    hot = hot_functions(mod)
    if not hot:
        return findings

    def _flag(node, fn, detail, what):
        esc = mod.directive_for(node, "allow-blocking-io")
        msg_extra = ""
        if esc is not None:
            if esc[1]:
                return
            msg_extra = (" [allow-blocking-io present but a REASON is "
                         "required: # lint: allow-blocking-io(<why>)]")
        root = hot[fn]
        where = mod.qualname(fn)
        via = "" if where == root else f" (reached from {root})"
        findings.append(Finding(
            checker="PT006", file=mod.rel, line=node.lineno,
            message=f"{what} in hot path {where}(){via}{msg_extra}",
            hint="serve the hot read from a cached snapshot (a poller "
                 "thread refreshes it), pass a bounded timeout=, or "
                 "annotate why it must block: "
                 "# lint: allow-blocking-io(<reason>)",
            context=where, detail=detail))

    for fn in hot:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = dotted_name(f)
            if name in _TIMEOUT_CALLS:
                if not _has_bounded_timeout(node):
                    _flag(node, fn, name.split(".")[-1],
                          f"{name}() without a bounded timeout=")
            elif (isinstance(f, ast.Attribute)
                    and f.attr in _RECV_METHODS
                    # plain Name receivers only would miss
                    # self.sock.recv(); flag any attribute form — the
                    # method names are specific enough that non-socket
                    # receivers are rare, and the escape hatch covers
                    # them
                    and name not in _TIMEOUT_CALLS):
                _flag(node, fn, f".{f.attr}()",
                      f"blocking socket read .{f.attr}()")
    return findings
