"""PT001 — recompile hazard (the ONE-compiled-program bar, PR 2/3/10).

The serving stack's whole latency story rests on "one compiled program
serves any request mix": per-slot device vectors instead of per-config
programs (PR 2), O(len(buckets)) prefill programs instead of
O(#distinct prompt lengths) (PR 3), exactly one extra program variant
per KV dtype (PR 10). The two ways this silently breaks:

1. a ``jax.jit`` / ``monitored_jit`` callable CONSTRUCTED per call — a
   fresh wrapper owns a fresh trace cache, so every invocation
   re-traces (and usually re-compiles). Blessed idioms: module-level
   construction, construction in a setup method (``__init__`` /
   ``warmup`` / ``reset_state`` / ``_build*`` / ``_init*`` / ``_make*``
   / ``setup*``) stored on ``self``, a keyed-cache store
   (``self._cache[key] = jit(...)`` — one program per key BY DESIGN),
   a memoized builder (``functools.lru_cache``/``cache``), or a builder
   that returns the jitted callable to a caller who stores it.
2. a Python-varying value traced as a regular argument: a wrapped
   function whose parameter NAME says "per-call-varying Python scalar"
   (``n_steps``, ``width``, ``draft_k``, ...) jitted without
   ``static_argnames`` re-compiles per distinct value with no cache
   bound and no cache-keyed intent recorded.

Escape hatch: ``# lint: allow-recompile(<reason>)`` on (or above) the
construction line, reason required.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Module, dotted_name

#: last segment + optional prefix that makes a call a jit construction
_JIT_LASTS = {"jit", "pjit", "monitored_jit"}
_JIT_PREFIXES = {"jax", "monitor", "mon", "_monitor", "monitoring"}

#: parameter names that (by this repo's conventions) carry per-call
#: Python-varying scalars — tracing them re-compiles per distinct value
STATIC_HINT_PARAMS = frozenset({
    "n_steps", "num_steps", "nsteps", "steps", "segment_steps",
    "width", "bucket", "chunk", "prefill_chunk", "draft_k",
    "block_size", "page_size", "n_layers",
})

_SETUP_PREFIXES = ("_build", "_init", "_make", "setup", "warmup")
_SETUP_NAMES = {"__init__", "reset_state", "warmup", "set_kv_dtype"}


def _is_jit_name(node: ast.AST) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] not in _JIT_LASTS:
        return False
    return len(parts) == 1 or parts[0] in _JIT_PREFIXES


def _is_setup(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    if fn.name in _SETUP_NAMES or fn.name.startswith(_SETUP_PREFIXES):
        return True
    for dec in fn.decorator_list:
        d = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        if d and d.split(".")[-1] in ("lru_cache", "cache"):
            return True
    return False


def _wrapped_params(mod: Module, call: ast.Call,
                    scope) -> List[str]:
    """Parameter names of the function the jit call wraps, when it is a
    local/nested def or lambda we can resolve (else [])."""
    if not call.args:
        return []
    target = call.args[0]
    if isinstance(target, ast.Lambda):
        return [a.arg for a in target.args.args]
    if not isinstance(target, ast.Name):
        return []
    # nearest def with that name in the enclosing scope chain
    scopes = []
    if scope is not None:
        scopes.append(scope)
        scopes.extend(a for a in mod.ancestors(scope)
                      if isinstance(a, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)))
    scopes.append(mod.tree)
    for s in scopes:
        for stmt in ast.walk(s):
            if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name == target.id):
                return [a.arg for a in stmt.args.args]
    return []


def _has_static_kw(call: ast.Call) -> bool:
    return any(kw.arg in ("static_argnames", "static_argnums")
               for kw in call.keywords)


def _assignment_shape(mod: Module, call: ast.Call) -> str:
    """How the construction's value is consumed: 'subscript' (keyed
    cache), 'self' (instance attr), 'local:<name>', 'return', 'call'
    (immediately invoked), 'arg' (passed along), or 'other'."""
    parent = mod.parent.get(call)
    if isinstance(parent, ast.Call) and parent.func is call:
        return "call"
    node, cur = call, parent
    while isinstance(cur, (ast.Tuple, ast.BinOp, ast.IfExp)):
        node, cur = cur, mod.parent.get(cur)
    if isinstance(cur, ast.Return):
        return "return"
    if isinstance(cur, ast.Call):
        return "arg"
    if isinstance(cur, (ast.Assign, ast.AnnAssign)):
        targets = (cur.targets if isinstance(cur, ast.Assign)
                   else [cur.target])
        for t in targets:
            if isinstance(t, ast.Subscript):
                return "subscript"
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in ("self", "cls")):
                return "self"
            if isinstance(t, ast.Name):
                return f"local:{t.id}"
    return "other"


def _local_called_or_cached(fn, name: str) -> str:
    """For a local-assigned jit: 'called' when the name is invoked in
    the same function (construct-and-call-per-invocation hazard),
    'cached' when it is stored into a subscript/attribute or returned
    (builder), else 'unused'."""
    called = cached = False
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == name):
            called = True
        if isinstance(node, ast.Assign):
            if any(isinstance(t, (ast.Subscript, ast.Attribute))
                   for t in node.targets):
                if any(isinstance(v, ast.Name) and v.id == name
                       for v in ast.walk(node.value)):
                    cached = True
        if isinstance(node, ast.Return) and node.value is not None:
            # returning the WRAPPER (bare name, possibly in a tuple)
            # hands ownership to the caller; `return fn(x)` does not —
            # the name nested under a Call is a per-invocation use
            v = node.value
            elems = [v] + (list(v.elts)
                           if isinstance(v, ast.Tuple) else [])
            if any(isinstance(e, ast.Name) and e.id == name
                   for e in elems):
                cached = True
    if cached:
        return "cached"
    return "called" if called else "unused"


def _lazy_init_guard(mod: Module, call: ast.Call) -> bool:
    """True for the guarded lazy-init idiom: the jit is assigned to
    ``self.X`` inside an ``if`` whose test mentions ``self.X`` (``if
    self.X is None: self.X = jit(...)``) — constructed once, like a
    keyed cache with one key."""
    attr = None
    cur = mod.parent.get(call)
    while cur is not None and not isinstance(cur, (ast.Assign,
                                                   ast.AnnAssign)):
        cur = mod.parent.get(cur)
    if cur is None:
        return False
    targets = (cur.targets if isinstance(cur, ast.Assign)
               else [cur.target])
    for t in targets:
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id in ("self", "cls")):
            attr = f"{t.value.id}.{t.attr}"
    if attr is None:
        return False
    for a in mod.ancestors(call):
        if isinstance(a, ast.If):
            try:
                if attr in ast.unparse(a.test):
                    return True
            except Exception:
                continue
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return False


def check_recompile_hazard(mod: Module) -> List[Finding]:
    findings: List[Finding] = []

    def _flag(node, detail, message, hint):
        esc = mod.directive_for(node, "allow-recompile")
        if esc is not None:
            if esc[1]:
                return
            message = ("allow-recompile requires a reason: "
                       "# lint: allow-recompile(<why>)")
        findings.append(Finding(
            checker="PT001", file=mod.rel, line=node.lineno,
            message=message, hint=hint,
            context=mod.scope_qualname(node), detail=detail))

    for node in ast.walk(mod.tree):
        # -- decorator form: @jax.jit on a def nested inside a function
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            outer = mod.enclosing_function(node)
            if outer is None:
                continue
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                # @functools.partial(jax.jit, ...) nests jit as an arg
                jitted = _is_jit_name(base) or (
                    isinstance(dec, ast.Call)
                    and any(_is_jit_name(a) for a in dec.args))
                if jitted and not _is_setup(outer):
                    _flag(node, f"jit-decorator:{node.name}",
                          f"@jit-decorated local def {node.name!r} is "
                          f"re-jitted every call of "
                          f"{mod.scope_qualname(node)}() — a fresh "
                          "wrapper re-traces per invocation",
                          "hoist to module level, build once in a "
                          "setup method, or store in a keyed cache")
            continue
        if not isinstance(node, ast.Call) or not _is_jit_name(node.func):
            continue
        fn = mod.enclosing_function(node)
        wrapped = (dotted_name(node.args[0]) if node.args else None) \
            or "lambda"
        detail = f"jit:{wrapped}"
        shape = _assignment_shape(mod, node)
        in_loop = False
        if fn is not None:
            for a in mod.ancestors(node):
                if a is fn:
                    break
                if isinstance(a, (ast.For, ast.While)):
                    in_loop = True
                    break

        # -- sub-check 2: python-varying param traced without
        #    static_argnames (applies wherever constructed, EXCEPT the
        #    keyed-cache idiom where the key IS the static value)
        if shape != "subscript" and not _has_static_kw(node):
            params = set(_wrapped_params(mod, node, fn))
            hits = sorted(params & STATIC_HINT_PARAMS)
            if hits:
                _flag(node, f"static:{wrapped}",
                      f"jit of {wrapped!r} traces python-varying "
                      f"parameter(s) {', '.join(hits)} without "
                      "static_argnames — each distinct value "
                      "re-compiles with no bound",
                      "add static_argnames=(...) or key a program "
                      "cache on the value")

        if fn is None:
            continue                     # module level: compiled once
        if shape == "subscript":
            continue                     # keyed cache: one program/key
        if shape == "call":
            _flag(node, detail,
                  f"jit({wrapped}) constructed and immediately called "
                  f"in {mod.scope_qualname(node)}() — re-traces on "
                  "every invocation",
                  "construct once (module level / setup method / "
                  "functools.cache) and call the stored wrapper")
            continue
        if in_loop:
            _flag(node, detail,
                  f"jit({wrapped}) constructed inside a loop in "
                  f"{mod.scope_qualname(node)}()",
                  "hoist out of the loop or store into a keyed cache "
                  "(cache[key] = jit(...))")
            continue
        if _is_setup(fn):
            continue                     # setup method: built once
        if shape == "self" and _lazy_init_guard(mod, node):
            continue                     # `if self._fn is None:` cache
        if shape == "self":
            _flag(node, detail,
                  f"jit({wrapped}) assigned to an instance attribute "
                  f"in non-setup method {mod.scope_qualname(node)}() — "
                  "re-constructed (and re-traced) per call",
                  "move construction to __init__/warmup/reset_state "
                  "or a _build*/_make* helper")
            continue
        if shape in ("return", "arg"):
            continue                     # builder handing off ownership
        if shape.startswith("local:"):
            use = _local_called_or_cached(fn, shape.split(":", 1)[1])
            if use == "called":
                _flag(node, detail,
                      f"jit({wrapped}) constructed into a local and "
                      f"called in the same function "
                      f"{mod.scope_qualname(node)}() — a fresh trace "
                      "cache per invocation",
                      "construct once (module level / setup method / "
                      "keyed cache) and reuse the wrapper")
            continue
        _flag(node, detail,
              f"jit({wrapped}) constructed in "
              f"{mod.scope_qualname(node)}() without a visible "
              "cache/return — likely re-constructed per call",
              "store at module level, on self in a setup method, or "
              "in a keyed cache")
    return findings
