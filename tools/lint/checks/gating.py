"""PT005 — flag gating: tracing/monitor seam work must branch on its
enable flag first (the near-zero-when-off bar, PR 1/8).

Both observability packages promise "one module-level bool branch and
nothing else" while disabled. That promise dies one ungated call site
at a time: a ``trace.event(...)`` whose kwargs are eagerly built, a
``counter().labels(...).inc()`` that allocates a bound series, a ring
append behind no branch. Two rules:

1. CALL SITES anywhere in the tree — a trace-recording call
   (``trace.event`` / ``trace.record`` / ``tracing.event`` ...) or a
   monitor mutation chain (``....labels(...).inc/.set/.observe/.dec``
   or ``monitor.counter/gauge/histogram(...).inc/...``) must be
   dominated by an enable check: lexically inside an ``if`` whose test
   mentions ``enabled``, or after an early-return gate
   (``if not ...enabled...: return``) in the same function.
   ``trace.span`` / ``.dump`` are exempt: they gate internally and
   return cheap nulls.
2. INTERNALS of ``paddle_tpu/monitor`` and ``paddle_tpu/tracing`` —
   the recording primitives themselves (``_ring.append(...)``,
   ``self._values[...] = ...`` stores) must sit behind the module
   ``_enabled`` bool the same two ways.

Escape hatch (reason required): ``# lint: allow-ungated(<reason>)`` —
e.g. a validation that must fail flag-independently (the
negative-counter guard), or an admin/export path that is never hot.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..core import Finding, Module, dotted_name

_TRACE_MODULES = {"trace", "tracing", "_trace", "_tracing"}
_TRACE_RECORDERS = {"event", "record"}
_MUTATORS = {"inc", "dec", "set", "observe"}
_CTORS = {"counter", "gauge", "histogram"}
_ENABLED_RE = re.compile(r"\benabled\b|\b_enabled\b")


def _test_mentions_enabled(test: ast.AST) -> bool:
    try:
        return bool(_ENABLED_RE.search(ast.unparse(test)))
    except Exception:
        return False


def _gated(mod: Module, node: ast.AST) -> bool:
    """Dominated by an enable branch: an ancestor ``if <...enabled...>``
    (anywhere up to the enclosing def), or an earlier top-level
    ``if <...enabled...>: return/raise`` early-exit in the same def."""
    fn = mod.enclosing_function(node)
    stop = fn if fn is not None else mod.tree
    prev = node
    for a in mod.ancestors(node):
        if isinstance(a, ast.If) and _test_mentions_enabled(a.test):
            # gated whether the work is in body or orelse: an
            # `if enabled: ... else: ...` made a deliberate choice
            return True
        if a is stop:
            break
        prev = a
    if fn is None:
        return False
    # early-return gate before this statement in the function body
    for stmt in fn.body:
        if stmt is prev or getattr(stmt, "lineno", 0) >= node.lineno:
            break
        if isinstance(stmt, ast.If) and _test_mentions_enabled(stmt.test) \
                and any(isinstance(s, (ast.Return, ast.Raise))
                        for s in stmt.body):
            return True
    return False


def _is_trace_record_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute)
            and f.attr in _TRACE_RECORDERS
            and isinstance(f.value, ast.Name)
            and f.value.id in _TRACE_MODULES)


def _is_monitor_mutation(node: ast.Call) -> Optional[str]:
    """'labels-chain' / 'ctor-chain' when this is a monitor instrument
    mutation, else None. The receiver chain must contain a ``.labels``
    call or a counter/gauge/histogram constructor call — that is what
    separates ``bound.inc()`` from ``threading.Event.set()``."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
        return None
    cur = f.value
    while True:
        if isinstance(cur, ast.Call):
            cf = cur.func
            if isinstance(cf, ast.Attribute) and cf.attr == "labels":
                return "labels-chain"
            name = dotted_name(cf)
            if name and name.split(".")[-1] in _CTORS:
                return "ctor-chain"
            cur = cf
        elif isinstance(cur, ast.Attribute):
            cur = cur.value
        else:
            return None


def check_flag_gating(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    internal = ("/monitor/" in "/" + mod.rel
                or "/tracing/" in "/" + mod.rel)

    def _flag(node, detail, what):
        esc = mod.directive_for(node, "allow-ungated")
        extra = ""
        if esc is not None:
            if esc[1]:
                return
            extra = " [allow-ungated present but a REASON is required]"
        ctx = mod.qualname(mod.enclosing_function(node) or mod.tree)
        findings.append(Finding(
            checker="PT005", file=mod.rel, line=node.lineno,
            message=f"{what} not gated on its enable flag — work runs "
                    f"even when the seam is off{extra}",
            hint="wrap in `if monitor.enabled():` / "
                 "`if trace.enabled():` (or gate the function with an "
                 "early return), or justify: "
                 "# lint: allow-ungated(<reason>)",
            context=ctx, detail=detail))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            if _is_trace_record_call(node) and not _gated(mod, node):
                f = node.func
                _flag(node, f"{f.value.id}.{f.attr}",
                      f"trace-recording call {f.value.id}.{f.attr}()")
            elif _is_monitor_mutation(node) and not _gated(mod, node):
                _flag(node, f"monitor.{node.func.attr}",
                      f"monitor mutation .{node.func.attr}() chain")
            elif internal and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append" \
                    and dotted_name(node.func.value) in ("_ring",) \
                    and not _gated(mod, node):
                _flag(node, "ring-append", "trace ring append")
        elif internal and isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "_values"
                        and not _gated(mod, node)):
                    _flag(node, "values-store",
                          "instrument value store (self._values[...])")
    return findings
