"""PT002 — blocking host sync reached from an annotated hot path
(the never-block-the-gap / lock-light-snapshot bar, PR 2-10).

Ground truth is the ``# lint: hot-path`` annotation on a def (the
scheduler gap, the decode segments, ``Server.load()`` and the router's
snapshot — MIGRATING.md "Static analysis annotations"). Hotness
propagates through the INTRA-module call graph: ``self.method()`` and
module-function calls reachable from a hot root are scanned too, so a
sync hidden in a helper is still caught. Cross-module edges (the
scheduler calling ``self.engine.decode_segment``) are NOT followed —
the engine's hot entry points carry their own annotations.

Flagged operations:

- ``.item()`` — blocking device->host scalar read;
- ``np.asarray(...)`` / ``np.array(...)`` — forces a device transfer
  when handed a device array (and is flagged even for host inputs:
  the reviewer writes the one-line reason, the lint can't know);
- ``jax.device_get`` / ``block_until_ready`` — explicit syncs;
- ``int(x)`` / ``float(x)`` where ``x`` mentions ``self.<attr>`` state
  or a ``jnp.``/``jax.`` expression — scalar coercion of a device
  value blocks on its computation (``bool`` is exempt: truthiness
  checks on host dicts/flags are idiomatic and device bools reach the
  host through ``np.asarray``, which is already flagged).

Escape hatch (reason REQUIRED): ``# lint: allow-host-sync(<reason>)``
on or above the flagged line — e.g. the decode segment's per-step
draft readback, which is the documented price of host proposers.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module, class_chain, dotted_name

_NP_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
# int()/float() only: device-bool reads in this codebase go through
# np.asarray (flagged above), while bool(self.<host dict/flag>) is an
# idiomatic truthiness check that would drown the signal
_COERCIONS = {"int", "float"}


def _collect_defs(mod: Module):
    """(module_functions, classes, methods[classname][name]) — nested
    defs are excluded from the lookup tables (they are scanned as part
    of their parent's body)."""
    mod_fns: Dict[str, ast.FunctionDef] = {}
    classes: Dict[str, ast.ClassDef] = {}
    methods: Dict[str, Dict[str, ast.FunctionDef]] = {}
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod_fns[node.name] = node
        elif isinstance(node, ast.ClassDef):
            classes[node.name] = node
            methods[node.name] = {
                m.name: m for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return mod_fns, classes, methods


def _callees(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(self-method names, bare function names) called in fn's body."""
    self_calls: Set[str] = set()
    bare_calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")):
            self_calls.add(f.attr)
        elif isinstance(f, ast.Name):
            bare_calls.add(f.id)
    return self_calls, bare_calls


def hot_functions(mod: Module) -> Dict[ast.AST, str]:
    """Transitively hot defs -> the root annotation that made them hot."""
    mod_fns, classes, methods = _collect_defs(mod)
    roots: List[Tuple[ast.AST, Optional[str], str]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and mod.ann.on_line(node.lineno, "hot-path") is not None:
            cls = mod.enclosing_class(node)
            roots.append((node, cls.name if cls else None,
                          mod.qualname(node)))
    hot: Dict[ast.AST, str] = {}
    todo = [(fn, cls, root) for fn, cls, root in roots]
    while todo:
        fn, clsname, root = todo.pop()
        if fn in hot:
            continue
        hot[fn] = root
        self_calls, bare_calls = _callees(fn)
        for name in bare_calls:
            target = mod_fns.get(name)
            if target is not None and target not in hot:
                todo.append((target, None, root))
        if clsname is None:
            continue
        mro = class_chain(classes[clsname], classes) \
            if clsname in classes else []
        for name in self_calls:
            for c in mro:
                target = methods.get(c.name, {}).get(name)
                if target is not None:
                    if target not in hot:
                        # scan the resolved method in the CALLER's
                        # class context so its own self-calls keep
                        # resolving through the subclass first
                        todo.append((target, clsname, root))
                    break
    return hot


def _mentions_device_state(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"):
            return True
        if isinstance(sub, ast.Name) and sub.id in ("jnp", "jax"):
            return True
    return False


def check_host_sync(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    hot = hot_functions(mod)
    if not hot:
        return findings

    def _flag(node, fn, detail, what):
        esc = mod.directive_for(node, "allow-host-sync")
        msg_extra = ""
        if esc is not None:
            if esc[1]:
                return
            msg_extra = (" [allow-host-sync present but a REASON is "
                         "required: # lint: allow-host-sync(<why>)]")
        root = hot[fn]
        where = mod.qualname(fn)
        via = "" if where == root else f" (reached from {root})"
        findings.append(Finding(
            checker="PT002", file=mod.rel, line=node.lineno,
            message=f"{what} in hot path {where}(){via}{msg_extra}",
            hint="hoist off the hot path, batch the read per gap, or "
                 "annotate why it must block: "
                 "# lint: allow-host-sync(<reason>)",
            context=where, detail=detail))

    for fn in hot:
        # walk only this def's OWN body: nested defs found in the walk
        # belong to fn (closures run as part of it) and are included
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = dotted_name(f)
            if isinstance(f, ast.Attribute) and f.attr == "item" \
                    and not node.args:
                _flag(node, fn, ".item()",
                      "blocking .item() device read")
            elif name in _NP_CALLS:
                _flag(node, fn, name.split(".", 1)[0] + "." +
                      name.split(".")[-1],
                      f"{name}() host transfer")
            elif name in _SYNC_CALLS or (
                    isinstance(f, ast.Attribute)
                    and f.attr == "block_until_ready"):
                _flag(node, fn, "block_until_ready"
                      if "block" in (name or f.attr)
                      else name, f"explicit device sync "
                      f"({name or f.attr})")
            elif (isinstance(f, ast.Name) and f.id in _COERCIONS
                    and len(node.args) == 1
                    and _mentions_device_state(node.args[0])):
                _flag(node, fn, f"{f.id}()",
                      f"{f.id}() scalar coercion of device state")
    return findings
