"""Checker registry: id -> function(Module) -> [Finding].

Each checker lives in its own module and encodes ONE invariant the
codebase already claims (see tools/lint/__init__ for the table and the
PR that established each bar)."""
from .recompile import check_recompile_hazard
from .host_sync import check_host_sync
from .series import check_series_lifecycle
from .locks import check_lock_discipline
from .gating import check_flag_gating
from .socket_io import check_socket_io

CHECKERS = {
    "PT001": check_recompile_hazard,
    "PT002": check_host_sync,
    "PT003": check_series_lifecycle,
    "PT004": check_lock_discipline,
    "PT005": check_flag_gating,
    "PT006": check_socket_io,
}

__all__ = ["CHECKERS"]
