"""paddle_tpu-lint — invariant-aware static analysis for this repo.

Ten PRs of serving work rest on invariants that were previously enforced
only by reviewer vigilance and after-the-fact regression tests. This
package encodes them as AST checkers that fail CI at the violating line:

========  ==================================================================
checker   invariant (and the PR that established it)
========  ==================================================================
PT001     recompile hazard: a ``jax.jit``/``monitored_jit`` callable
          constructed per call (inside a method/loop body), or a
          Python-varying value traced without ``static_argnames`` —
          the ONE-compiled-program bar (PR 2/3/10).
PT002     host sync in a hot path: ``.item()`` / ``np.asarray`` /
          ``jax.device_get`` / ``block_until_ready`` / device-scalar
          coercion reached from a ``# lint: hot-path`` function —
          the never-block-the-gap / lock-light ``load()`` bar (PR 9).
PT003     series lifecycle: a monitor Counter/Gauge/Histogram created
          with an instance label (server/engine/pool/router/loader/fit)
          must be retired in the owning class's close/shutdown —
          the leak class PR 8's retirement test caught at runtime.
PT004     lock discipline: fields declared ``# guarded-by: self._lock``
          accessed outside a ``with self._lock`` block (PR 4/9's
          threaded serving classes).
PT005     flag gating: monitor/trace recording work not branching on its
          enable flag first — the near-zero-when-off bar (PR 1/8).
PT006     blocking socket I/O in a hot path: ``urlopen`` / connection
          constructors without a bounded ``timeout=``, or raw
          ``.recv``/``.accept``/``.getresponse`` reads reached from a
          ``# lint: hot-path`` function — the cached-snapshot-only bar
          the cross-process fleet's routing seam rides on (PR 17).
========  ==================================================================

Run ``python -m tools.lint paddle_tpu/``; see ``tools/lint/baseline.json``
for the triaged pre-existing findings (the bar is "no NEW violations").
The annotation grammar (``# lint: ...`` / ``# guarded-by: ...``) is
documented in MIGRATING.md under "Static analysis annotations".
"""
from .core import (BaselineError, Finding, Module, apply_baseline,
                   default_baseline_path, fingerprint_findings,
                   generate_baseline, lint_paths, lint_source,
                   load_baseline, write_baseline)
from .checks import CHECKERS

__all__ = [
    "BaselineError", "Finding", "Module", "CHECKERS",
    "lint_paths", "lint_source",
    "load_baseline", "write_baseline", "apply_baseline",
    "generate_baseline", "fingerprint_findings", "default_baseline_path",
]
