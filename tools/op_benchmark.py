"""Per-kernel op benchmark (reference analog: tools/test_op_benchmark.sh +
test/cpp/fluid/benchmark/op_tester.cc — the op-perf CI gate's measurement
half).

Runs the framework's hot kernels at bench shapes and writes one JSON
object per op. Pair with ``check_op_benchmark_result.py`` to gate
regressions between two runs.

    python tools/op_benchmark.py --out ops_now.json [--ops rms,rope,...]

Honest timing through the remote-dispatch tunnel: chained loop bodies (no
hoisting), scalar host readback, two iteration counts differenced to
cancel the constant dispatch cost.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timed(fn, args, iters=10):
    import jax
    import jax.numpy as jnp

    def loop(args, n):
        def body(_, a):
            out = fn(*a)
            s = jax.tree.map(lambda x: jnp.sum(x).astype(jnp.float32), out)
            tot = jax.tree.reduce(lambda p, q: p + q, s) * 1e-30
            return (a[0] + tot.astype(a[0].dtype),) + tuple(a[1:])

        out = jax.lax.fori_loop(0, n, body, args)
        return jnp.sum(out[0].astype(jnp.float32).ravel()[:128])

    jit = jax.jit(loop, static_argnums=(1,))
    lo, hi = iters, iters * 6
    _ = float(jit(args, lo))
    _ = float(jit(args, hi))
    t0 = time.perf_counter()
    _ = float(jit(args, lo))
    t1 = time.perf_counter()
    _ = float(jit(args, hi))
    t2 = time.perf_counter()
    return max(((t2 - t1) - (t1 - t0)) / (hi - lo), 1e-9)


def build_ops():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_kernels as pk
    from paddle_tpu.ops.flash_attention_kernel import flash_attention_bhsd

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        B, H, S, D, HID = 8, 8, 2048, 128, 1024
    else:  # CPU smoke: tiny shapes so interpret-mode kernels finish
        B, H, S, D, HID = 1, 2, 128, 32, 64
    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    q = jax.random.normal(key, (B, H, S, D), dt)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), dt)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), dt)
    x = jax.random.normal(key, (B, S, HID), dt)
    g = jnp.ones((HID,), dt)
    qr = jax.random.normal(key, (B, S, H, D), dt)
    cos = jax.random.normal(key, (S, D // 2), dt)
    sin = jax.random.normal(key, (S, D // 2), dt)
    att = 2 * B * H * S * S * D

    ops = {
        "flash_fwd": (lambda q, k, v: flash_attention_bhsd(
            q, k, v, causal=True), (q, k, v), att),
        "flash_fwd_bwd": (lambda q, k, v: jax.grad(
            lambda a, b, c: jnp.sum(flash_attention_bhsd(
                a, b, c, causal=True).astype(jnp.float32)),
            argnums=(0, 1, 2))(q, k, v), (q, k, v), 3 * att),
        "rms_norm": (lambda x, g: pk.rms_norm(x, g), (x, g), None),
        "fused_rope": (lambda a: pk.fused_rope(a, cos, sin), (qr,), None),
        "matmul_hid_4x": (
            lambda a, w: a.reshape(-1, HID) @ w,
            (x, jax.random.normal(key, (HID, 4 * HID), dt)),
            2 * B * S * HID * 4 * HID),
    }
    return ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="op_bench.json")
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default all)")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax

    ops = build_ops()
    names = args.ops.split(",") if args.ops else list(ops)
    results = {}
    for name in names:
        fn, fargs, flops = ops[name]
        try:
            t = timed(fn, fargs, iters=args.iters)
            rec = {"ms": round(t * 1e3, 4)}
            if flops:
                rec["tflops"] = round(flops / t / 1e12, 2)
            results[name] = rec
        except Exception as e:  # noqa: BLE001 — record, keep going
            results[name] = {"error": str(e)[:200]}
        print(json.dumps({name: results[name]}), flush=True)
    payload = {"platform": jax.devices()[0].platform,
               "device_kind": jax.devices()[0].device_kind,
               "ops": results}
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
