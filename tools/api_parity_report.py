"""API-parity report: paddle_tpu surface vs the reference's public
`__all__` lists, module by module.

The reference tree is not importable here (CUDA deps), so its surface is
parsed textually from each module's ``__all__``. Ours is imported live.

    python tools/api_parity_report.py [--ref /root/reference] [--out X.json]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (reference module path relative to python/paddle, our attribute path)
MODULES = [
    ("__init__.py", ""),
    ("tensor/__init__.py", None),          # folded into top-level
    ("nn/__init__.py", "nn"),
    ("nn/functional/__init__.py", "nn.functional"),
    ("nn/initializer/__init__.py", "nn.initializer"),
    ("nn/utils/__init__.py", "nn.utils"),
    ("optimizer/__init__.py", "optimizer"),
    ("optimizer/lr.py", "optimizer.lr"),
    ("amp/__init__.py", "amp"),
    ("autograd/__init__.py", "autograd"),
    ("distributed/__init__.py", "distributed"),
    ("distributed/fleet/__init__.py", "distributed.fleet"),
    ("io/__init__.py", "io"),
    ("jit/__init__.py", "jit"),
    ("static/__init__.py", "static"),
    ("vision/__init__.py", "vision"),
    ("vision/models/__init__.py", "vision.models"),
    ("vision/transforms/__init__.py", "vision.transforms"),
    ("vision/datasets/__init__.py", "vision.datasets"),
    ("vision/ops.py", "vision.ops"),
    ("audio/__init__.py", "audio"),
    ("audio/functional/__init__.py", "audio.functional"),
    ("audio/features/__init__.py", "audio.features"),
    ("text/__init__.py", "text"),
    ("metric/__init__.py", "metric"),
    ("linalg.py", "linalg"),
    ("fft.py", "fft"),
    ("signal.py", "signal"),
    ("sparse/__init__.py", "sparse"),
    ("distribution/__init__.py", "distribution"),
    ("quantization/__init__.py", "quantization"),
    ("geometric/__init__.py", "geometric"),
    ("incubate/__init__.py", "incubate"),
    ("profiler/__init__.py", "profiler"),
    ("device/__init__.py", "device"),
    ("onnx/__init__.py", "onnx"),
    ("hub.py", "hub"),
    ("regularizer.py", "regularizer"),
    ("callbacks.py", "callbacks"),
    ("utils/__init__.py", "utils"),
]

_SKIP = {
    # names meaningless off-GPU/XPU or tied to reference internals
    "is_compiled_with_rocm", "is_compiled_with_xpu", "is_compiled_with_ipu",
    "is_compiled_with_custom_device", "IPUPlace", "XPUPlace",
    "CustomPlace", "set_ipu_shard", "IpuStrategy", "IpuCompiledProgram",
}


def parse_all(path: str):
    try:
        src = open(path, encoding="utf-8").read()
    except OSError:
        return None
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
    if not m:
        return []
    names = re.findall(r"['\"]([A-Za-z_][\w.]*)['\"]", m.group(1))
    return [n for n in names if n not in _SKIP]


def our_surface(attr_path: str):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle

    obj = paddle
    if attr_path:
        for part in attr_path.split("."):
            obj = getattr(obj, part)
    return set(dir(obj))


_GATED_RE = re.compile(
    r"raise\s+(NotImplementedError|RuntimeError|ImportError)\b"
    r"|_gated\(|_require\(")


def classify(obj) -> str:
    """Behavior smoke (VERDICT r2 #10: 'present' != 'works'). A name is
    'gated' when its body (or __init__/__call__) immediately raises — the
    raise-on-call stub pattern — so 100% name parity can't hide stubs.

    'ok' = resolves and is not a gated stub; 'value' = non-callable
    constant/module. Static inspection, not invocation: calling arbitrary
    public fns without their example args would be both unsafe and a
    false negative generator."""
    import inspect

    if not callable(obj):
        return "value"
    fn = obj
    if isinstance(obj, type):
        fn = obj.__dict__.get("__init__", obj.__init__)
    try:
        fn = inspect.unwrap(fn)
        src = inspect.getsource(fn)
    except (OSError, TypeError):
        return "ok"          # C/builtin: callable by construction
    # only the first statements matter: a guard deep in a big function is
    # input validation, not a stub
    head = "\n".join(src.splitlines()[:12])
    if _GATED_RE.search(head) and "def " in src:
        body_lines = [l.strip() for l in src.splitlines()
                      if l.strip() and not l.strip().startswith(
                          ("#", "def ", "@", '"', "'", "r'", 'r"'))]
        # a stub's FIRST real statement raises
        if body_lines and body_lines[0].startswith("raise "):
            return "gated"
    return "ok"


def smoke_module(attr_path: str, names):
    """Classify each present name → {'ok': [...], 'gated': [...],
    'value': [...]}."""
    import paddle_tpu as paddle

    obj = paddle
    if attr_path:
        for part in attr_path.split("."):
            obj = getattr(obj, part)
    out = {"ok": [], "gated": [], "value": []}
    for n in names:
        root = n.split(".")[0]
        try:
            target = getattr(obj, root)
        except AttributeError:
            continue
        out[classify(target)].append(root)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    base = os.path.join(args.ref, "python", "paddle")

    report = {}
    total_ref = total_have = total_gated = total_ok = 0
    top_extra = parse_all(os.path.join(base, "tensor/__init__.py")) or []
    for rel, ours in MODULES:
        if ours is None:
            continue
        ref_names = parse_all(os.path.join(base, rel))
        if ref_names is None:
            continue
        if rel == "__init__.py":
            ref_names = sorted(set(ref_names) | set(top_extra))
        try:
            have = our_surface(ours)
        except AttributeError:
            have = set()
        missing = sorted(n for n in ref_names if n.split(".")[0] not in have)
        smoke = smoke_module(ours, ref_names)
        total_ref += len(ref_names)
        total_have += len(ref_names) - len(missing)
        total_gated += len(smoke["gated"])
        total_ok += len(smoke["ok"]) + len(smoke["value"])
        report["paddle." + ours if ours else "paddle"] = {
            "ref": len(ref_names), "missing": missing,
            "gated": sorted(smoke["gated"])}
        tag = "OK " if not missing else f"{len(missing):3d} missing"
        gtag = "" if not smoke["gated"] else f"  {len(smoke['gated'])} gated"
        print(f"{('paddle.' + ours).rstrip('.'):34s} "
              f"{len(ref_names) - len(missing):4d}/{len(ref_names):4d} "
              f"{tag}{gtag}")
    pct = 100.0 * total_have / max(total_ref, 1)
    wpct = 100.0 * total_ok / max(total_ref, 1)
    print(f"\nTOTAL present {total_have}/{total_ref} ({pct:.1f}%)   "
          f"works (present & not gated) {total_ok}/{total_ref} "
          f"({wpct:.1f}%), gated stubs: {total_gated}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"total_ref": total_ref, "total_have": total_have,
                       "total_works": total_ok, "total_gated": total_gated,
                       "pct": round(pct, 2), "works_pct": round(wpct, 2),
                       "modules": report}, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
