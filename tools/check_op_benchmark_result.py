"""Op-perf regression gate (reference analog:
tools/check_op_benchmark_result.py — compares a PR's op benchmark log
against the develop baseline and fails on regressions).

    python tools/check_op_benchmark_result.py \
        --baseline ops_base.json --new ops_now.json [--threshold 0.10]

Exit code 1 when any op slowed down by more than ``threshold`` (relative).
"""
from __future__ import annotations

import argparse
import json
import sys


def compare(baseline: dict, new: dict, threshold: float):
    failures, report = [], []
    base_ops = baseline.get("ops", baseline)
    new_ops = new.get("ops", new)
    for name, base in sorted(base_ops.items()):
        cur = new_ops.get(name)
        if cur is None:
            report.append(f"  {name:20s} MISSING from new run")
            failures.append(name)
            continue
        if "error" in base or "error" in cur:
            report.append(f"  {name:20s} error "
                          f"({cur.get('error', base.get('error'))[:60]})")
            if "error" in cur and "error" not in base:
                failures.append(name)
            continue
        b, c = base["ms"], cur["ms"]
        rel = (c - b) / b if b else 0.0
        flag = "REGRESSION" if rel > threshold else \
            ("improved" if rel < -threshold else "ok")
        report.append(f"  {name:20s} {b:9.3f}ms -> {c:9.3f}ms "
                      f"({rel * 100:+6.1f}%) {flag}")
        if rel > threshold:
            failures.append(name)
    return failures, report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--baseline", required=True)
    ap.add_argument("--new", required=True)
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="max allowed relative slowdown (default 10%%)")
    args = ap.parse_args()
    with open(args.baseline) as f:
        baseline = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    failures, report = compare(baseline, new, args.threshold)
    print("\n".join(report))
    if failures:
        print(f"FAILED: {len(failures)} op(s) regressed beyond "
              f"{args.threshold * 100:.0f}%: {', '.join(failures)}")
        sys.exit(1)
    print("PASSED: no op regressions")


if __name__ == "__main__":
    main()
