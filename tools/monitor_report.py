#!/usr/bin/env python
"""Pretty-print paddle_tpu.monitor snapshots.

Reads either a JSONL file written by ``monitor.write_jsonl()`` (or the
BENCH_* trajectory — same record shape) or a live ``/metrics.json``
endpoint started with ``monitor.start_http_server()``, and prints the
latest value per (metric, labels) as an aligned table.

Usage::

    python tools/monitor_report.py run.jsonl            # file
    python tools/monitor_report.py -                    # stdin
    python tools/monitor_report.py --url http://127.0.0.1:8080
    python tools/monitor_report.py run.jsonl --filter kv_   # substring
    python tools/monitor_report.py --url ... --serving  # serving view
    # request-lifecycle trace view (a paddle_tpu.tracing chrome-JSON
    # export or flight-recorder dump): per-phase latency table +
    # the top-K slowest requests with their dominant phase
    python tools/monitor_report.py --trace serve_trace.json --top 5
    # SLO/goodput view (a saved GET /stats body, or fetched live):
    # per-tenant goodput/burn table + fleet-vs-replica percentiles
    python tools/monitor_report.py --slo stats.json
    python tools/monitor_report.py --url http://127.0.0.1:8000 --slo
    # program-ledger roofline view (a saved GET /profile body, or
    # fetched live): per-program FLOPs/MFU/%-of-step table with a
    # memory-bound/compute-bound verdict per program
    python tools/monitor_report.py --profile profile.json
    python tools/monitor_report.py --url http://127.0.0.1:8000 --profile
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple


def _fmt_value(v) -> str:
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e12:
            return str(int(v))
        if abs(v) >= 1e6 or (0 < abs(v) < 1e-3):
            return f"{v:.4g}"
        return f"{v:.4f}"
    return str(v)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def load_jsonl(stream) -> List[dict]:
    """Parse JSONL records, keeping the LATEST record per
    (metric, labels) — a trajectory file holds many snapshots."""
    latest: Dict[Tuple, dict] = {}
    for line in stream:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # bench logs interleave free text with records
        if "metric" not in rec:
            continue
        key = (rec["metric"],
               tuple(sorted((rec.get("labels") or {}).items())))
        latest[key] = rec
    return [latest[k] for k in sorted(latest)]


def load_snapshot(snap: dict) -> List[dict]:
    """Flatten a monitor.snapshot() dict into jsonl-shaped records."""
    out = []
    for name, meta in sorted(snap.get("metrics", {}).items()):
        for s in meta.get("samples", []):
            rec = {"metric": name, "labels": s.get("labels") or {}}
            if meta.get("type") == "histogram":
                rec["value"] = s.get("mean", 0.0)
                rec["count"] = s.get("count")
                rec["sum"] = s.get("sum")
            else:
                rec["value"] = s.get("value")
            out.append(rec)
    return out


# the serving metric families (scheduler + engine admission + KV pool)
# --serving selects: one flag shows the whole online-serving picture,
# fault-isolation columns included (the paddle_tpu_serving_ prefix
# covers faults_total{kind,site}, restarts_total, the degraded gauge,
# and recovery_seconds alongside queue depth / TTFT / TPOT)
SERVING_FAMILIES = (
    "paddle_tpu_serving_",              # queue depth, TTFT, TPOT, events,
    #                                     faults, restarts, degraded,
    #                                     recovery, kv_pressure
    "paddle_tpu_router_",               # fleet tier: requests by
    #                                     {replica,outcome}, failovers,
    #                                     breaker_state gauge, replica
    #                                     restarts
    "paddle_tpu_requests_total",        # engine lifecycle events
    "paddle_tpu_generated_tokens_total",
    "paddle_tpu_decode_tokens_per_sec",
    "paddle_tpu_kv_admission_seconds",
    "paddle_tpu_kv_page_occupancy_ratio",
    "paddle_tpu_kv_pages",              # pool free/used by state +
    #                                     kv_dtype (int8 pools hold ~2x
    #                                     pages at fixed HBM)
    "paddle_tpu_kv_quant_bytes_saved_total",  # int8 KV: HBM bytes the
    #                                     quantized layout avoided for
    #                                     claimed pages, per pool
    "paddle_tpu_kv_preemptions_total",  # memory-pressure preemptions
    #                                     by reason (pressure /
    #                                     unsatisfiable)
    "paddle_tpu_kv_prefix_",            # prefix-cache hits_total and
    #                                     tokens_saved_total per pool
    "paddle_tpu_kv_shared_pages",       # refcount>1 pages (sharing
    #                                     multiplier) per pool
    "paddle_tpu_prefill_",              # bucket/chunk admissions, warmup
    "paddle_tpu_lora_",                 # multi-tenant LoRA: requests
    #                                     per {engine,adapter} and the
    #                                     adapters_resident gauge
    "paddle_tpu_spec_",                 # speculative-decode draft tokens
    #                                     {engine,outcome=proposed|
    #                                     accepted} — per-engine
    #                                     acceptance rate is
    #                                     accepted/proposed
)


def render(records: List[dict], filter_: str = "",
           serving: bool = False) -> str:
    rows = []
    for rec in records:
        name = rec["metric"]
        if serving and not any(name.startswith(f)
                               for f in SERVING_FAMILIES):
            continue
        if filter_ and filter_ not in name:
            continue
        extra = ""
        if "count" in rec and rec["count"] is not None:
            extra = (f"n={rec['count']}"
                     + (f" sum={_fmt_value(rec['sum'])}"
                        if rec.get("sum") is not None else ""))
        rows.append((name + _fmt_labels(rec.get("labels") or {}),
                     _fmt_value(rec.get("value")),
                     rec.get("unit", ""), extra))
    if not rows:
        return "(no metrics)"
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    lines = [f"{'METRIC':<{w0}}  {'VALUE':>{w1}}  UNIT",
             "-" * (w0 + w1 + 12)]
    for name, val, unit, extra in rows:
        line = f"{name:<{w0}}  {val:>{w1}}  {unit}"
        if extra:
            line += f"  ({extra})"
        lines.append(line.rstrip())
    return "\n".join(lines)


def _percentile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def render_trace(doc: dict, top: int = 5) -> str:
    """Per-phase latency table + top-K slowest requests for a
    ``paddle_tpu.tracing`` chrome-JSON export / flight-recorder dump.

    Phases aggregate every event by name (span durations in seconds;
    instants count only); requests aggregate by the ``rid`` each event
    carries (batch-wide segment events fan out to every entry of their
    ``rids`` list). A request's latency is the span of its events
    (first begin to last end), and its DOMINANT phase is the one with
    the largest summed span duration — the "which phase ate the time"
    answer for the slow tail."""
    evs = doc.get("traceEvents", [])
    other = doc.get("otherData") or {}
    phases: Dict[str, List[float]] = {}
    reqs: Dict[str, dict] = {}
    for e in evs:
        name = e.get("name", "?")
        ts = float(e.get("ts", 0.0)) / 1e6       # µs -> s
        dur = float(e.get("dur", 0.0)) / 1e6
        phases.setdefault(name, []).append(dur)
        args = e.get("args") or {}
        rids = []
        if args.get("rid") is not None:
            rids.append(args["rid"])
        for r in (args.get("rids") or []):
            rids.append(r)
        for r in rids:
            d = reqs.setdefault(
                str(r), {"t0": ts, "t1": ts + dur, "by": {}})
            d["t0"] = min(d["t0"], ts)
            d["t1"] = max(d["t1"], ts + dur)
            d["by"][name] = d["by"].get(name, 0.0) + dur
    lines = []
    if other.get("reason"):
        lines.append(f"flight-recorder dump: reason="
                     f"{other['reason']!r} pid={other.get('pid')}")
    if not phases:
        lines.append("(no trace events)")
        return "\n".join(lines)
    w = max(len(n) for n in phases)
    lines.append(f"{'PHASE':<{w}}  {'COUNT':>6}  {'p50(s)':>10}"
                 f"  {'p99(s)':>10}")
    lines.append("-" * (w + 32))
    for name in sorted(phases, key=lambda n: -sum(phases[n])):
        xs = phases[name]
        lines.append(f"{name:<{w}}  {len(xs):>6}"
                     f"  {_percentile(xs, 50):>10.5f}"
                     f"  {_percentile(xs, 99):>10.5f}")
    slow = sorted(reqs.items(), key=lambda kv: -(kv[1]["t1"]
                                                 - kv[1]["t0"]))[:top]
    if slow:
        lines.append("")
        lines.append(f"top {len(slow)} slowest requests:")
        for rid, d in slow:
            total = d["t1"] - d["t0"]
            if d["by"]:
                dom, ddur = max(d["by"].items(), key=lambda kv: kv[1])
                share = ddur / total if total > 0 else 0.0
                lines.append(f"  {rid:<16} {total:>9.4f}s  dominant: "
                             f"{dom} ({ddur:.4f}s, {share:.0%})")
            else:
                lines.append(f"  {rid:<16} {total:>9.4f}s")
    return "\n".join(lines)


def _fmt_opt(v, fmt: str = ".4f", none: str = "-") -> str:
    if v is None:
        return none
    try:
        return format(v, fmt)
    except (TypeError, ValueError):
        return str(v)


def render_slo(doc: dict) -> str:
    """Per-tenant goodput/burn table + fleet-vs-replica percentile
    comparison for a ``GET /stats`` snapshot (``paddle_tpu.monitor.slo``
    — a Server's own rollup or a Router's merged fleet rollup; both
    serve the same shape).

    The fleet row of the comparison is computed by MERGING replica
    digests (exact), so a replica whose p50/p99 sits far above it is
    the skew detector's slow-but-alive story told in percentiles —
    slow replicas are marked ``*SLOW*``."""
    lines = []
    owner = doc.get("router") or doc.get("server") or "?"
    pol = doc.get("policy")
    if pol:
        th = ", ".join(f"{k.replace('_p99_s', '')}<={v}s"
                       for k, v in pol.items()
                       if k.endswith("_p99_s") and v is not None)
        lines.append(
            f"slo [{owner}]: {th}, goodput target "
            f"{pol.get('goodput_target')}, burn windows "
            f"{pol.get('fast_window_s')}s/{pol.get('slow_window_s')}s")
    else:
        lines.append(f"slo [{owner}]: no policy armed (digests only — "
                     "pass Server(slo_policy=...) to score goodput)")
    tens = doc.get("tenants") or {}
    if tens:
        lines.append("")
        w = max(6, max(len(t) for t in tens))
        lines.append(f"{'TENANT':<{w}}  {'REQS':>6}  {'GOODPUT':>8}"
                     f"  {'BURN_F':>7}  {'BURN_S':>7}  {'FAILED':>6}"
                     f"  {'TOKENS':>8}  {'KV_PAGE_S':>10}")
        lines.append("-" * (w + 62))
        for t in sorted(tens):
            v = tens[t]
            lines.append(
                f"{t:<{w}}  {v.get('requests', 0):>6}"
                f"  {_fmt_opt(v.get('goodput')):>8}"
                f"  {_fmt_opt(v.get('burn_fast'), '.2f'):>7}"
                f"  {_fmt_opt(v.get('burn_slow'), '.2f'):>7}"
                f"  {v.get('failed', 0):>6}"
                f"  {v.get('tokens', 0):>8}"
                f"  {_fmt_opt(v.get('kv_page_seconds'), '.1f'):>10}")
    mets = doc.get("metrics") or {}
    if mets:
        lines.append("")
        lines.append(f"{'METRIC':<12}{'TENANT':<10}  {'COUNT':>6}"
                     f"  {'p50(s)':>10}  {'p90(s)':>10}  {'p99(s)':>10}")
        lines.append("-" * 64)
        for metric in ("ttft", "tpot", "queue_wait", "e2e"):
            per = mets.get(metric)
            if not per:
                continue
            for t in sorted(per, key=lambda k: (k != "*", k)):
                s = per[t]
                lines.append(
                    f"{metric:<12}{t:<10}  {s.get('count', 0):>6}"
                    f"  {_fmt_opt(s.get('p50'), '.5f'):>10}"
                    f"  {_fmt_opt(s.get('p90'), '.5f'):>10}"
                    f"  {_fmt_opt(s.get('p99'), '.5f'):>10}")
    reps = doc.get("replicas") or []
    if reps:
        lines.append("")
        lines.append("fleet vs replicas (all-tenant '*'; fleet rows "
                     "are digest MERGES, not averages):")
        lines.append(f"{'WHO':<16}  {'METRIC':<6}  {'COUNT':>6}"
                     f"  {'p50(s)':>10}  {'p99(s)':>10}")
        lines.append("-" * 56)
        for metric in ("ttft", "tpot"):
            agg = mets.get(metric, {}).get("*")
            if agg:
                lines.append(f"{'fleet':<16}  {metric:<6}"
                             f"  {agg.get('count', 0):>6}"
                             f"  {_fmt_opt(agg.get('p50'), '.5f'):>10}"
                             f"  {_fmt_opt(agg.get('p99'), '.5f'):>10}")
            for e in reps:
                rm = (e.get("metrics") or {}).get(metric, {}).get("*")
                tag = (f"replica{e.get('replica')}"
                       + (" *SLOW*" if e.get("slow") else ""))
                if rm:
                    lines.append(
                        f"{tag:<16}  {metric:<6}"
                        f"  {rm.get('count', 0):>6}"
                        f"  {_fmt_opt(rm.get('p50'), '.5f'):>10}"
                        f"  {_fmt_opt(rm.get('p99'), '.5f'):>10}")
        skew = doc.get("skew") or {}
        slow = skew.get("slow_replicas")
        lines.append("")
        lines.append(
            f"skew: factor {skew.get('factor')}, slow replicas "
            f"{slow if slow else 'none'} (slow = rolling TPOT p50 > "
            f"factor x fleet median; deprioritized, breaker untouched)")
    if not tens and not mets:
        lines.append("(no SLO data recorded — is FLAGS_enable_monitor "
                     "on?)")
    return "\n".join(lines)


def render_control(doc: dict) -> str:
    """Overload-control view of a ``paddle_tpu.tracing`` chrome-JSON
    export / flight-recorder dump: the brownout-ladder timeline
    (every ``control.rung`` transition with the occupancy that drove
    it), burn-rate sheds grouped by tenant and reason, shed-storm
    flight-dump triggers, and the router's elastic ``control.scale``
    decisions. Timestamps are seconds relative to the first event in
    the ring — the same clock the --trace view uses."""
    evs = doc.get("traceEvents", [])
    other = doc.get("otherData") or {}
    t0 = min((float(e.get("ts", 0.0)) for e in evs), default=0.0)
    rungs, storms, scales = [], [], []
    sheds: Dict[str, Dict[str, int]] = {}
    for e in evs:
        name = e.get("name", "?")
        if not name.startswith("control."):
            continue
        ts = (float(e.get("ts", 0.0)) - t0) / 1e6    # µs -> s
        a = e.get("args") or {}
        if name == "control.rung":
            rungs.append((ts, a.get("prev"), a.get("rung"),
                          a.get("action", "?"), a.get("occupancy")))
        elif name == "control.shed":
            by = sheds.setdefault(str(a.get("tenant")), {})
            r = str(a.get("reason", "?"))
            by[r] = by.get(r, 0) + 1
        elif name == "control.shed_storm":
            storms.append((ts, a.get("count"), a.get("window_s")))
        elif name == "control.scale":
            scales.append((ts, a.get("action", "?"), a.get("replica"),
                           a.get("queue_depth"), a.get("burn")))
    lines = []
    if other.get("reason"):
        lines.append(f"flight-recorder dump: reason="
                     f"{other['reason']!r} pid={other.get('pid')}")
    if not (rungs or sheds or storms or scales):
        lines.append("(no control.* events — was the control plane "
                     "armed and tracing on?)")
        return "\n".join(lines)
    if rungs:
        lines.append("brownout ladder transitions:")
        lines.append(f"  {'t(s)':>9}  {'RUNG':>9}  {'ACTION':<14}"
                     f"{'OCCUPANCY':>10}")
        for ts, prev, rung, action, occ in rungs:
            lines.append(f"  {ts:>9.3f}  {_fmt_opt(prev, 'd'):>4}->"
                         f"{_fmt_opt(rung, 'd'):<4} {action:<14}"
                         f"{_fmt_opt(occ, '.3f'):>10}")
        lines.append("")
    if sheds:
        lines.append("burn-rate sheds by tenant:")
        w = max(len(t) for t in sheds)
        for tenant in sorted(sheds,
                             key=lambda t: -sum(sheds[t].values())):
            by = sheds[tenant]
            detail = ", ".join(f"{r}={n}"
                               for r, n in sorted(by.items()))
            lines.append(f"  {tenant:<{w}}  {sum(by.values()):>6}  "
                         f"({detail})")
        lines.append("")
    if storms:
        lines.append(f"shed storms (flight-dump triggers): "
                     f"{len(storms)}")
        for ts, count, win in storms:
            lines.append(f"  t={ts:.3f}s  {count} sheds inside "
                         f"{win}s")
        lines.append("")
    if scales:
        lines.append("elastic scale decisions:")
        for ts, action, rep, depth, burn in scales:
            lines.append(f"  t={ts:.3f}s  {action:<5} replica "
                         f"{_fmt_opt(rep, 'd')}  (queue_depth="
                         f"{_fmt_opt(depth, '.1f')}, burn="
                         f"{_fmt_opt(burn, '.2f')})")
    return "\n".join(lines).rstrip()


def render_wire(doc: dict) -> str:
    """Wire-resilience view of a ``paddle_tpu.tracing`` chrome-JSON
    export: the exactly-once event timeline (idempotent submit
    retries, mid-stream resumes with their from_token, server-side
    idem attaches, KV integrity rejects) plus per-request resume
    chains proving the resume-before-failover order
    (route -> stream -> resume -> finish). Timestamps are seconds
    relative to the first event in the ring, the --trace clock."""
    evs = doc.get("traceEvents", [])
    t0 = min((float(e.get("ts", 0.0)) for e in evs), default=0.0)
    rows, counts = [], {}
    chains: Dict[str, List[str]] = {}
    for e in evs:
        name = e.get("name", "?")
        a = e.get("args") or {}
        rid = a.get("rid")
        if name in ("route", "failover", "first_token", "finish",
                    "wire.resume"):
            if rid is not None:
                chains.setdefault(str(rid), []).append(name)
        if name not in ("wire.retry", "wire.resume", "idem.attach",
                        "kv.integrity_reject"):
            continue
        counts[name] = counts.get(name, 0) + 1
        ts = (float(e.get("ts", 0.0)) - t0) / 1e6    # µs -> s
        if name == "wire.retry":
            detail = (f"attempt={a.get('attempt')} "
                      f"wait={_fmt_opt(a.get('wait_s'), '.3f')}s "
                      f"cause={a.get('cause')}")
        elif name == "wire.resume":
            detail = (f"attempt={a.get('attempt')} "
                      f"from_token={a.get('from_token')} "
                      f"cause={a.get('cause')}")
        elif name == "idem.attach":
            detail = (f"rid={a.get('rid')} "
                      f"from_token={a.get('from_token')} "
                      f"live={a.get('live')}")
        else:
            detail = str(a.get("error", ""))[:72]
        rows.append((ts, name, rid, detail))
    if not rows:
        return ("(no wire.*/idem.*/kv.integrity_reject events — was "
                "tracing on while the wire was faulted?)")
    lines = ["wire-resilience events:",
             f"  {'t(s)':>9}  {'EVENT':<20} {'RID':<22} DETAIL"]
    for ts, name, rid, detail in sorted(rows):
        lines.append(f"  {ts:>9.3f}  {name:<20} "
                     f"{str(rid) if rid is not None else '-':<22} "
                     f"{detail}")
    lines.append("")
    lines.append("counts: " + ", ".join(
        f"{k}={v}" for k, v in sorted(counts.items())))
    resumed = {r: c for r, c in chains.items() if "wire.resume" in c}
    if resumed:
        lines.append("")
        lines.append("resume chains (resume-before-failover order):")
        for rid in sorted(resumed):
            lines.append(f"  {rid}: " + " -> ".join(resumed[rid]))
    return "\n".join(lines)


def _fmt_units(v, none: str = "-") -> str:
    """1.23e12 -> '1.23T' — roofline numbers span 9 orders."""
    if v is None:
        return none
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                           (1e3, "k")):
        if abs(v) >= thresh:
            return f"{v / thresh:.2f}{suffix}"
    return f"{v:.3g}"


def render_profile(doc: dict) -> str:
    """Roofline table for a ``GET /profile`` snapshot (``monitor.ledger``
    — a Server's own shard or a Router's merge-exact fleet rollup;
    both serve the same shape).

    One row per compiled program, sorted by total dispatch seconds:
    share of total ledger time, dispatch/compile counts, p50 wall,
    XLA cost-analysis FLOPs, achieved MFU against the backend peak
    table, arithmetic intensity, and the roofline verdict — intensity
    below the machine balance means the program is MEMORY-bound (more
    MXU would not help; feeding it would)."""
    progs = doc.get("programs") or {}
    pk = doc.get("peaks") or {}
    owner = doc.get("router") or doc.get("server") or "?"
    lines = []
    if pk:
        lines.append(
            f"profile [{owner}]: {pk.get('device_kind')} "
            f"({pk.get('source')}) peak "
            f"{_fmt_units(pk.get('peak_flops'))}FLOP/s, "
            f"{_fmt_units(pk.get('peak_bytes_per_s'))}B/s, "
            f"balance {pk.get('machine_balance', 0):.1f} FLOP/B")
    else:
        lines.append(f"profile [{owner}]")
    if not progs:
        lines.append("(no programs recorded — is FLAGS_enable_ledger "
                     "on and the workload warmed?)")
        return "\n".join(lines)
    total = doc.get("total_seconds") or sum(
        p.get("total_seconds", 0.0) for p in progs.values()) or 1.0
    order = doc.get("top") or sorted(
        progs, key=lambda p: -progs[p].get("total_seconds", 0.0))
    w = max(len(p) for p in progs)
    lines.append(
        f"{'PROGRAM':<{w}}  {'%TIME':>6}  {'DISP':>7}  {'COMP':>4}"
        f"  {'p50(s)':>10}  {'FLOPS':>8}  {'MFU':>7}  {'AI':>7}"
        f"  VERDICT")
    lines.append("-" * (w + 70))
    for pid in order:
        p = progs.get(pid)
        if p is None:
            continue
        ts = p.get("total_seconds", 0.0)
        summ = p.get("summary") or {}
        lines.append(
            f"{pid:<{w}}  {ts / total:>6.1%}"
            f"  {p.get('dispatches', 0):>7}  {p.get('compiles', 0):>4}"
            f"  {_fmt_opt(summ.get('p50'), '.6f'):>10}"
            f"  {_fmt_units(p.get('flops')):>8}"
            f"  {_fmt_opt(p.get('mfu'), '.4f'):>7}"
            f"  {_fmt_opt(p.get('intensity'), '.1f'):>7}"
            f"  {p.get('bound', '-')}")
    comp = sum(p.get("compile_seconds", 0.0) for p in progs.values())
    lines.append("")
    lines.append(
        f"{len(progs)} programs, {total:.4f}s dispatch time, "
        f"{comp:.3f}s compile time"
        + (f" across {doc['replicas']} replicas"
           if doc.get("replicas") else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="JSONL file from monitor.write_jsonl(), or '-' "
                         "for stdin")
    ap.add_argument("--url", default=None,
                    help="base URL of a monitor HTTP endpoint (fetches "
                         "<url>/metrics.json)")
    ap.add_argument("--filter", default="", dest="filter_",
                    metavar="SUBSTR", help="only metrics containing SUBSTR")
    ap.add_argument("--serving", action="store_true",
                    help="only the online-serving families (queue depth, "
                         "TTFT, TPOT, request events, tokens/sec, KV "
                         "admission + occupancy + preemptions/pressure, "
                         "faults/restarts/degraded/recovery)")
    ap.add_argument("--trace", default=None, metavar="JSON",
                    help="render a paddle_tpu.tracing chrome-JSON "
                         "export / flight-recorder dump instead: "
                         "per-phase p50/p99 table + the --top slowest "
                         "requests with their dominant phase")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest-requests rows in the --trace view")
    ap.add_argument("--control", default=None, metavar="JSON",
                    help="render the overload-control view of a "
                         "chrome-JSON trace export / flight-recorder "
                         "dump instead: brownout-ladder rung "
                         "timeline, burn-rate sheds by tenant/"
                         "reason, shed-storm triggers, elastic "
                         "scale decisions")
    ap.add_argument("--wire", default=None, metavar="JSON",
                    help="render the wire-resilience view of a "
                         "chrome-JSON trace export instead: the "
                         "exactly-once event timeline (submit "
                         "retries, mid-stream resumes, idem "
                         "attaches, KV integrity rejects) and the "
                         "per-request resume chains (serve_bench "
                         "--wire-chaos --trace-out writes one)")
    ap.add_argument("--slo", nargs="?", const="", default=None,
                    metavar="JSON",
                    help="render a GET /stats SLO snapshot instead: "
                         "per-tenant goodput/burn table + "
                         "fleet-vs-replica percentile comparison. "
                         "Pass a file (a saved /stats body, or a "
                         "monitor JSONL dump — falls back to the slo "
                         "metric families), or bare --slo with --url "
                         "to fetch <url>/stats live")
    ap.add_argument("--profile", nargs="?", const="", default=None,
                    metavar="JSON",
                    help="render a GET /profile program-ledger "
                         "snapshot instead: per-program roofline "
                         "table (%%-of-time, MFU, arithmetic "
                         "intensity, memory/compute-bound verdict). "
                         "Pass a saved /profile body, or bare "
                         "--profile with --url to fetch live")
    args = ap.parse_args(argv)

    if args.profile is not None:
        if not args.profile and not args.url:
            print("--profile needs a snapshot file or --url",
                  file=sys.stderr)
            return 2
        if not args.profile:
            from urllib.request import urlopen

            with urlopen(args.url.rstrip("/") + "/profile",
                         timeout=10) as resp:
                print(render_profile(json.load(resp)))
            return 0
        with open(args.profile) as f:
            print(render_profile(json.load(f)))
        return 0
    if args.trace:
        with open(args.trace) as f:
            print(render_trace(json.load(f), top=args.top))
        return 0
    if args.control:
        with open(args.control) as f:
            print(render_control(json.load(f)))
        return 0
    if args.wire:
        with open(args.wire) as f:
            print(render_wire(json.load(f)))
        return 0
    if args.slo is not None:
        if not args.slo and not args.url:
            print("--slo needs a snapshot file or --url",
                  file=sys.stderr)
            return 2
        if not args.slo:
            from urllib.request import urlopen

            with urlopen(args.url.rstrip("/") + "/stats",
                         timeout=10) as resp:
                print(render_slo(json.load(resp)))
            return 0
        with open(args.slo) as f:
            text = f.read()
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
        if isinstance(doc, dict) and ("tenants" in doc
                                      or "metrics" in doc):
            print(render_slo(doc))
            return 0
        # not a /stats body: treat it as a monitor JSONL dump and show
        # the SLO metric families (goodput gauge, miss counters,
        # per-tenant cost, the router slow gauge) in the plain table
        slo_families = ("paddle_tpu_serving_goodput",
                        "paddle_tpu_serving_slo_misses_total",
                        "paddle_tpu_serving_tenant_",
                        "paddle_tpu_router_replica_slow")
        records = [r for r in load_jsonl(text.splitlines())
                   if any(r["metric"].startswith(f)
                          for f in slo_families)]
        print(render(records, args.filter_))
        return 0
    if args.url:
        from urllib.request import urlopen

        url = args.url.rstrip("/")
        if not url.endswith("/metrics.json"):
            url += "/metrics.json"
        with urlopen(url, timeout=10) as resp:
            records = load_snapshot(json.load(resp))
    elif args.path == "-" or args.path is None:
        records = load_jsonl(sys.stdin)
    else:
        with open(args.path) as f:
            records = load_jsonl(f)

    print(render(records, args.filter_, serving=args.serving))
    return 0


if __name__ == "__main__":
    sys.exit(main())
