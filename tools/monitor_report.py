#!/usr/bin/env python
"""Pretty-print paddle_tpu.monitor snapshots.

Reads either a JSONL file written by ``monitor.write_jsonl()`` (or the
BENCH_* trajectory — same record shape) or a live ``/metrics.json``
endpoint started with ``monitor.start_http_server()``, and prints the
latest value per (metric, labels) as an aligned table.

Usage::

    python tools/monitor_report.py run.jsonl            # file
    python tools/monitor_report.py -                    # stdin
    python tools/monitor_report.py --url http://127.0.0.1:8080
    python tools/monitor_report.py run.jsonl --filter kv_   # substring
    python tools/monitor_report.py --url ... --serving  # serving view
    # request-lifecycle trace view (a paddle_tpu.tracing chrome-JSON
    # export or flight-recorder dump): per-phase latency table +
    # the top-K slowest requests with their dominant phase
    python tools/monitor_report.py --trace serve_trace.json --top 5
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple


def _fmt_value(v) -> str:
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e12:
            return str(int(v))
        if abs(v) >= 1e6 or (0 < abs(v) < 1e-3):
            return f"{v:.4g}"
        return f"{v:.4f}"
    return str(v)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def load_jsonl(stream) -> List[dict]:
    """Parse JSONL records, keeping the LATEST record per
    (metric, labels) — a trajectory file holds many snapshots."""
    latest: Dict[Tuple, dict] = {}
    for line in stream:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # bench logs interleave free text with records
        if "metric" not in rec:
            continue
        key = (rec["metric"],
               tuple(sorted((rec.get("labels") or {}).items())))
        latest[key] = rec
    return [latest[k] for k in sorted(latest)]


def load_snapshot(snap: dict) -> List[dict]:
    """Flatten a monitor.snapshot() dict into jsonl-shaped records."""
    out = []
    for name, meta in sorted(snap.get("metrics", {}).items()):
        for s in meta.get("samples", []):
            rec = {"metric": name, "labels": s.get("labels") or {}}
            if meta.get("type") == "histogram":
                rec["value"] = s.get("mean", 0.0)
                rec["count"] = s.get("count")
                rec["sum"] = s.get("sum")
            else:
                rec["value"] = s.get("value")
            out.append(rec)
    return out


# the serving metric families (scheduler + engine admission + KV pool)
# --serving selects: one flag shows the whole online-serving picture,
# fault-isolation columns included (the paddle_tpu_serving_ prefix
# covers faults_total{kind,site}, restarts_total, the degraded gauge,
# and recovery_seconds alongside queue depth / TTFT / TPOT)
SERVING_FAMILIES = (
    "paddle_tpu_serving_",              # queue depth, TTFT, TPOT, events,
    #                                     faults, restarts, degraded,
    #                                     recovery, kv_pressure
    "paddle_tpu_router_",               # fleet tier: requests by
    #                                     {replica,outcome}, failovers,
    #                                     breaker_state gauge, replica
    #                                     restarts
    "paddle_tpu_requests_total",        # engine lifecycle events
    "paddle_tpu_generated_tokens_total",
    "paddle_tpu_decode_tokens_per_sec",
    "paddle_tpu_kv_admission_seconds",
    "paddle_tpu_kv_page_occupancy_ratio",
    "paddle_tpu_kv_pages",              # pool free/used by state +
    #                                     kv_dtype (int8 pools hold ~2x
    #                                     pages at fixed HBM)
    "paddle_tpu_kv_quant_bytes_saved_total",  # int8 KV: HBM bytes the
    #                                     quantized layout avoided for
    #                                     claimed pages, per pool
    "paddle_tpu_kv_preemptions_total",  # memory-pressure preemptions
    #                                     by reason (pressure /
    #                                     unsatisfiable)
    "paddle_tpu_kv_prefix_",            # prefix-cache hits_total and
    #                                     tokens_saved_total per pool
    "paddle_tpu_kv_shared_pages",       # refcount>1 pages (sharing
    #                                     multiplier) per pool
    "paddle_tpu_prefill_",              # bucket/chunk admissions, warmup
    "paddle_tpu_lora_",                 # multi-tenant LoRA: requests
    #                                     per {engine,adapter} and the
    #                                     adapters_resident gauge
    "paddle_tpu_spec_",                 # speculative-decode draft tokens
    #                                     {engine,outcome=proposed|
    #                                     accepted} — per-engine
    #                                     acceptance rate is
    #                                     accepted/proposed
)


def render(records: List[dict], filter_: str = "",
           serving: bool = False) -> str:
    rows = []
    for rec in records:
        name = rec["metric"]
        if serving and not any(name.startswith(f)
                               for f in SERVING_FAMILIES):
            continue
        if filter_ and filter_ not in name:
            continue
        extra = ""
        if "count" in rec and rec["count"] is not None:
            extra = (f"n={rec['count']}"
                     + (f" sum={_fmt_value(rec['sum'])}"
                        if rec.get("sum") is not None else ""))
        rows.append((name + _fmt_labels(rec.get("labels") or {}),
                     _fmt_value(rec.get("value")),
                     rec.get("unit", ""), extra))
    if not rows:
        return "(no metrics)"
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    lines = [f"{'METRIC':<{w0}}  {'VALUE':>{w1}}  UNIT",
             "-" * (w0 + w1 + 12)]
    for name, val, unit, extra in rows:
        line = f"{name:<{w0}}  {val:>{w1}}  {unit}"
        if extra:
            line += f"  ({extra})"
        lines.append(line.rstrip())
    return "\n".join(lines)


def _percentile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def render_trace(doc: dict, top: int = 5) -> str:
    """Per-phase latency table + top-K slowest requests for a
    ``paddle_tpu.tracing`` chrome-JSON export / flight-recorder dump.

    Phases aggregate every event by name (span durations in seconds;
    instants count only); requests aggregate by the ``rid`` each event
    carries (batch-wide segment events fan out to every entry of their
    ``rids`` list). A request's latency is the span of its events
    (first begin to last end), and its DOMINANT phase is the one with
    the largest summed span duration — the "which phase ate the time"
    answer for the slow tail."""
    evs = doc.get("traceEvents", [])
    other = doc.get("otherData") or {}
    phases: Dict[str, List[float]] = {}
    reqs: Dict[str, dict] = {}
    for e in evs:
        name = e.get("name", "?")
        ts = float(e.get("ts", 0.0)) / 1e6       # µs -> s
        dur = float(e.get("dur", 0.0)) / 1e6
        phases.setdefault(name, []).append(dur)
        args = e.get("args") or {}
        rids = []
        if args.get("rid") is not None:
            rids.append(args["rid"])
        for r in (args.get("rids") or []):
            rids.append(r)
        for r in rids:
            d = reqs.setdefault(
                str(r), {"t0": ts, "t1": ts + dur, "by": {}})
            d["t0"] = min(d["t0"], ts)
            d["t1"] = max(d["t1"], ts + dur)
            d["by"][name] = d["by"].get(name, 0.0) + dur
    lines = []
    if other.get("reason"):
        lines.append(f"flight-recorder dump: reason="
                     f"{other['reason']!r} pid={other.get('pid')}")
    if not phases:
        lines.append("(no trace events)")
        return "\n".join(lines)
    w = max(len(n) for n in phases)
    lines.append(f"{'PHASE':<{w}}  {'COUNT':>6}  {'p50(s)':>10}"
                 f"  {'p99(s)':>10}")
    lines.append("-" * (w + 32))
    for name in sorted(phases, key=lambda n: -sum(phases[n])):
        xs = phases[name]
        lines.append(f"{name:<{w}}  {len(xs):>6}"
                     f"  {_percentile(xs, 50):>10.5f}"
                     f"  {_percentile(xs, 99):>10.5f}")
    slow = sorted(reqs.items(), key=lambda kv: -(kv[1]["t1"]
                                                 - kv[1]["t0"]))[:top]
    if slow:
        lines.append("")
        lines.append(f"top {len(slow)} slowest requests:")
        for rid, d in slow:
            total = d["t1"] - d["t0"]
            if d["by"]:
                dom, ddur = max(d["by"].items(), key=lambda kv: kv[1])
                share = ddur / total if total > 0 else 0.0
                lines.append(f"  {rid:<16} {total:>9.4f}s  dominant: "
                             f"{dom} ({ddur:.4f}s, {share:.0%})")
            else:
                lines.append(f"  {rid:<16} {total:>9.4f}s")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="JSONL file from monitor.write_jsonl(), or '-' "
                         "for stdin")
    ap.add_argument("--url", default=None,
                    help="base URL of a monitor HTTP endpoint (fetches "
                         "<url>/metrics.json)")
    ap.add_argument("--filter", default="", dest="filter_",
                    metavar="SUBSTR", help="only metrics containing SUBSTR")
    ap.add_argument("--serving", action="store_true",
                    help="only the online-serving families (queue depth, "
                         "TTFT, TPOT, request events, tokens/sec, KV "
                         "admission + occupancy + preemptions/pressure, "
                         "faults/restarts/degraded/recovery)")
    ap.add_argument("--trace", default=None, metavar="JSON",
                    help="render a paddle_tpu.tracing chrome-JSON "
                         "export / flight-recorder dump instead: "
                         "per-phase p50/p99 table + the --top slowest "
                         "requests with their dominant phase")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest-requests rows in the --trace view")
    args = ap.parse_args(argv)

    if args.trace:
        with open(args.trace) as f:
            print(render_trace(json.load(f), top=args.top))
        return 0
    if args.url:
        from urllib.request import urlopen

        url = args.url.rstrip("/")
        if not url.endswith("/metrics.json"):
            url += "/metrics.json"
        with urlopen(url, timeout=10) as resp:
            records = load_snapshot(json.load(resp))
    elif args.path == "-" or args.path is None:
        records = load_jsonl(sys.stdin)
    else:
        with open(args.path) as f:
            records = load_jsonl(f)

    print(render(records, args.filter_, serving=args.serving))
    return 0


if __name__ == "__main__":
    sys.exit(main())
