#!/usr/bin/env python
"""Pretty-print paddle_tpu.monitor snapshots.

Reads either a JSONL file written by ``monitor.write_jsonl()`` (or the
BENCH_* trajectory — same record shape) or a live ``/metrics.json``
endpoint started with ``monitor.start_http_server()``, and prints the
latest value per (metric, labels) as an aligned table.

Usage::

    python tools/monitor_report.py run.jsonl            # file
    python tools/monitor_report.py -                    # stdin
    python tools/monitor_report.py --url http://127.0.0.1:8080
    python tools/monitor_report.py run.jsonl --filter kv_   # substring
    python tools/monitor_report.py --url ... --serving  # serving view
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple


def _fmt_value(v) -> str:
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e12:
            return str(int(v))
        if abs(v) >= 1e6 or (0 < abs(v) < 1e-3):
            return f"{v:.4g}"
        return f"{v:.4f}"
    return str(v)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def load_jsonl(stream) -> List[dict]:
    """Parse JSONL records, keeping the LATEST record per
    (metric, labels) — a trajectory file holds many snapshots."""
    latest: Dict[Tuple, dict] = {}
    for line in stream:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # bench logs interleave free text with records
        if "metric" not in rec:
            continue
        key = (rec["metric"],
               tuple(sorted((rec.get("labels") or {}).items())))
        latest[key] = rec
    return [latest[k] for k in sorted(latest)]


def load_snapshot(snap: dict) -> List[dict]:
    """Flatten a monitor.snapshot() dict into jsonl-shaped records."""
    out = []
    for name, meta in sorted(snap.get("metrics", {}).items()):
        for s in meta.get("samples", []):
            rec = {"metric": name, "labels": s.get("labels") or {}}
            if meta.get("type") == "histogram":
                rec["value"] = s.get("mean", 0.0)
                rec["count"] = s.get("count")
                rec["sum"] = s.get("sum")
            else:
                rec["value"] = s.get("value")
            out.append(rec)
    return out


# the serving metric families (scheduler + engine admission + KV pool)
# --serving selects: one flag shows the whole online-serving picture,
# fault-isolation columns included (the paddle_tpu_serving_ prefix
# covers faults_total{kind,site}, restarts_total, the degraded gauge,
# and recovery_seconds alongside queue depth / TTFT / TPOT)
SERVING_FAMILIES = (
    "paddle_tpu_serving_",              # queue depth, TTFT, TPOT, events,
    #                                     faults, restarts, degraded,
    #                                     recovery, kv_pressure
    "paddle_tpu_requests_total",        # engine lifecycle events
    "paddle_tpu_generated_tokens_total",
    "paddle_tpu_decode_tokens_per_sec",
    "paddle_tpu_kv_admission_seconds",
    "paddle_tpu_kv_page_occupancy_ratio",
    "paddle_tpu_kv_pages",              # pool free/used by state
    "paddle_tpu_kv_preemptions_total",  # memory-pressure preemptions
    #                                     by reason (pressure /
    #                                     unsatisfiable)
    "paddle_tpu_kv_prefix_",            # prefix-cache hits_total and
    #                                     tokens_saved_total per pool
    "paddle_tpu_kv_shared_pages",       # refcount>1 pages (sharing
    #                                     multiplier) per pool
    "paddle_tpu_prefill_",              # bucket/chunk admissions, warmup
    "paddle_tpu_spec_",                 # speculative-decode draft tokens
    #                                     {engine,outcome=proposed|
    #                                     accepted} — per-engine
    #                                     acceptance rate is
    #                                     accepted/proposed
)


def render(records: List[dict], filter_: str = "",
           serving: bool = False) -> str:
    rows = []
    for rec in records:
        name = rec["metric"]
        if serving and not any(name.startswith(f)
                               for f in SERVING_FAMILIES):
            continue
        if filter_ and filter_ not in name:
            continue
        extra = ""
        if "count" in rec and rec["count"] is not None:
            extra = (f"n={rec['count']}"
                     + (f" sum={_fmt_value(rec['sum'])}"
                        if rec.get("sum") is not None else ""))
        rows.append((name + _fmt_labels(rec.get("labels") or {}),
                     _fmt_value(rec.get("value")),
                     rec.get("unit", ""), extra))
    if not rows:
        return "(no metrics)"
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    lines = [f"{'METRIC':<{w0}}  {'VALUE':>{w1}}  UNIT",
             "-" * (w0 + w1 + 12)]
    for name, val, unit, extra in rows:
        line = f"{name:<{w0}}  {val:>{w1}}  {unit}"
        if extra:
            line += f"  ({extra})"
        lines.append(line.rstrip())
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="JSONL file from monitor.write_jsonl(), or '-' "
                         "for stdin")
    ap.add_argument("--url", default=None,
                    help="base URL of a monitor HTTP endpoint (fetches "
                         "<url>/metrics.json)")
    ap.add_argument("--filter", default="", dest="filter_",
                    metavar="SUBSTR", help="only metrics containing SUBSTR")
    ap.add_argument("--serving", action="store_true",
                    help="only the online-serving families (queue depth, "
                         "TTFT, TPOT, request events, tokens/sec, KV "
                         "admission + occupancy + preemptions/pressure, "
                         "faults/restarts/degraded/recovery)")
    args = ap.parse_args(argv)

    if args.url:
        from urllib.request import urlopen

        url = args.url.rstrip("/")
        if not url.endswith("/metrics.json"):
            url += "/metrics.json"
        with urlopen(url, timeout=10) as resp:
            records = load_snapshot(json.load(resp))
    elif args.path == "-" or args.path is None:
        records = load_jsonl(sys.stdin)
    else:
        with open(args.path) as f:
            records = load_jsonl(f)

    print(render(records, args.filter_, serving=args.serving))
    return 0


if __name__ == "__main__":
    sys.exit(main())
