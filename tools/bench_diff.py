"""bench_diff — metric-by-metric comparison of two BENCH records.

The repo accumulates ``BENCH_r*.json`` rounds (and ``serve_bench`` /
``bench.py`` JSONL logs), but until now comparing two rounds was a
human squinting at numbers — which is how a perf regression ships
silently. This tool makes the comparison mechanical and CI-able:

    python -m tools.bench_diff OLD.json NEW.json          # two files
    python -m tools.bench_diff --dir .                    # newest two
    python -m tools.bench_diff --dir . --baseline BASE.json
    python -m tools.bench_diff OLD.json NEW.json --threshold 0.05
    python -m tools.bench_diff NEW.json --write-baseline BASE.json

Exit status: 0 when nothing regressed (identical records compare
clean by construction), 1 on any regression past threshold, 2 on
usage/load errors — so ``experiments/tpu_session.sh`` and CI can gate
on it directly.

**Direction-aware**: a +20% on ``tokens_per_sec`` is an improvement;
a +20% on ``tpot_p50`` is a regression. Direction is classified from
the metric name (latency/seconds/overhead → lower-better;
throughput/goodput/mfu/hit-rate → higher-better) with the record's
``unit`` as a fallback; unclassifiable metrics are reported
informationally and never fail the gate.

**Format-tolerant** — accepts every shape the repo produces:
- the root ``BENCH_r*.json`` wrapper ``{"n", "cmd", "rc", "tail",
  "parsed"}`` (records are parsed out of the embedded stdout tail);
- raw JSONL from ``bench.py`` / ``tools/serve_bench.py`` (one
  ``{"metric", "value", "unit", ...}`` object per line, non-JSON
  lines skipped);
- a JSON array of such records;
- a ``--write-baseline`` file this tool wrote earlier.

**Provenance-aware**: when both sides carry an ``env`` header
(``bench_env`` record or wrapper field — PR 16 provenance stamping),
mismatched backend / device_kind / device_count prints a WARNING —
cross-machine comparisons are unsound and should be read as such.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# metric-name substrings → direction. First list wins on conflict
# ("tokens_per_sec_overhead" would be odd, but overhead is the gate).
_LOWER_BETTER = (
    "ttft", "tpot", "latency", "seconds", "compile", "overhead",
    "occupancy", "recovery", "p50", "p90", "p99", "stall", "loss",
    "bytes", "cost", "miss", "preempt", "evict", "syncs",
)
_HIGHER_BETTER = (
    "tokens_per_sec", "throughput", "goodput", "survival", "capacity",
    "speedup", "hit_rate", "tokens_saved", "mfu", "accept", "tok_s",
    "per_chip", "bandwidth", "flops",
)
_LOWER_UNITS = ("s", "ms", "us", "seconds", "x (on/off)", "bytes")
_HIGHER_UNITS = ("tokens/s", "tokens/s/chip", "req/s", "1 (ratio)")


def classify(metric: str, unit: str = "") -> Optional[str]:
    """'lower' | 'higher' | None (unknown — informational only)."""
    low = metric.lower()
    for sub in _HIGHER_BETTER:
        if sub in low:
            return "higher"
    for sub in _LOWER_BETTER:
        if sub in low:
            return "lower"
    u = (unit or "").lower()
    if u in _HIGHER_UNITS:
        return "higher"
    if u in _LOWER_UNITS:
        return "lower"
    return None


def _records_from_text(text: str) -> List[Dict[str, Any]]:
    """Pull ``{"metric": ...}`` records out of mixed stdout (JSONL
    interleaved with XLA warnings — the wrapper's ``tail``)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def load_records(path: str) -> Tuple[List[Dict[str, Any]],
                                     Optional[Dict[str, Any]]]:
    """(records, env_header) from any supported file shape."""
    with open(path) as f:
        text = f.read()
    recs: List[Dict[str, Any]] = []
    env: Optional[Dict[str, Any]] = None
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if isinstance(doc, dict) and "tail" in doc:        # BENCH_r wrapper
        recs = _records_from_text(str(doc.get("tail", "")))
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed \
                and not any(r.get("metric") == parsed.get("metric")
                            for r in recs):
            recs.append(parsed)
        env = doc.get("env")
    elif isinstance(doc, dict) and "records" in doc:   # our baseline
        recs = list(doc["records"])
        env = doc.get("env")
    elif isinstance(doc, list):                        # JSON array
        recs = [r for r in doc if isinstance(r, dict) and "metric" in r]
    elif isinstance(doc, dict) and "metric" in doc:    # single record
        recs = [doc]
    else:                                              # JSONL / mixed
        recs = _records_from_text(text)
    for r in recs:                       # env header travels as a record
        if r.get("metric") == "bench_env" and env is None:
            env = r
    recs = [r for r in recs if r.get("metric") != "bench_env"
            and isinstance(r.get("value"), (int, float))]
    return recs, env


def index(recs: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Latest record per metric name (later lines win — the JSONL
    convention everywhere else in the repo)."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in recs:
        out[str(r["metric"])] = r
    return out


def diff(old: Dict[str, Dict[str, Any]],
         new: Dict[str, Dict[str, Any]],
         threshold: float) -> Tuple[List[dict], List[dict], List[dict]]:
    """(regressions, improvements, infos) over the shared metric set."""
    regressions, improvements, infos = [], [], []
    for metric in sorted(set(old) & set(new)):
        ov = float(old[metric]["value"])
        nv = float(new[metric]["value"])
        unit = new[metric].get("unit", old[metric].get("unit", ""))
        if ov == 0:
            ratio = None
            delta = None
        else:
            ratio = nv / ov
            delta = ratio - 1.0
        direction = classify(metric, unit)
        row = {"metric": metric, "old": ov, "new": nv, "unit": unit,
               "delta": delta, "direction": direction}
        if delta is None or direction is None:
            infos.append(row)
            continue
        bad = delta > threshold if direction == "lower" \
            else delta < -threshold
        good = delta < -threshold if direction == "lower" \
            else delta > threshold
        if bad:
            regressions.append(row)
        elif good:
            improvements.append(row)
        else:
            infos.append(row)
    return regressions, improvements, infos


def _fmt(row: dict) -> str:
    d = row["delta"]
    pct = f"{d * 100:+.1f}%" if d is not None else "n/a"
    arrow = {"lower": "↓ better", "higher": "↑ better",
             None: "?"}[row["direction"]]
    return (f"  {row['metric']:<48} {row['old']:>12.6g} -> "
            f"{row['new']:>12.6g} {pct:>8}  [{arrow}]"
            + (f" {row['unit']}" if row["unit"] else ""))


def _env_mismatch(env_a: Optional[dict], env_b: Optional[dict]
                  ) -> List[str]:
    if not env_a or not env_b:
        return []
    out = []
    for k in ("backend", "device_kind", "device_count", "jax"):
        va, vb = env_a.get(k), env_b.get(k)
        if va is not None and vb is not None and va != vb:
            out.append(f"{k}: {va!r} vs {vb!r}")
    return out


def _newest_two(dirpath: str) -> Tuple[str, str]:
    cands = sorted(glob.glob(os.path.join(dirpath, "BENCH_r*.json")))
    if len(cands) < 2:
        raise SystemExit(
            f"--dir {dirpath}: need >= 2 BENCH_r*.json files, "
            f"found {len(cands)}")
    return cands[-2], cands[-1]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="direction-aware diff of two BENCH record files; "
                    "exit 1 on regression")
    ap.add_argument("files", nargs="*",
                    help="OLD NEW (two files), or one NEW with "
                         "--baseline/--write-baseline")
    ap.add_argument("--dir", help="compare the newest two "
                    "BENCH_r*.json in this directory")
    ap.add_argument("--baseline",
                    help="compare FILES[0] (or --dir newest) against "
                         "this baseline instead of the prior round")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative change that counts as a "
                         "regression/improvement (default 0.10)")
    ap.add_argument("--write-baseline", metavar="OUT",
                    help="write FILES[0]'s records (+env) as a "
                         "baseline file and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdict on stdout")
    args = ap.parse_args(argv)

    try:
        if args.write_baseline:
            if len(args.files) != 1:
                ap.error("--write-baseline takes exactly one input file")
            recs, env = load_records(args.files[0])
            with open(args.write_baseline, "w") as f:
                json.dump({"records": sorted(
                    (index(recs)).values(),
                    key=lambda r: r["metric"]), "env": env,
                    "source": os.path.basename(args.files[0])},
                    f, indent=1)
            print(f"baseline: {len(index(recs))} metrics -> "
                  f"{args.write_baseline}")
            return 0
        if args.dir:
            old_path, new_path = _newest_two(args.dir)
            if args.files:
                new_path = args.files[0]
        elif len(args.files) == 2:
            old_path, new_path = args.files
        elif len(args.files) == 1 and args.baseline:
            old_path, new_path = args.baseline, args.files[0]
        else:
            ap.error("give OLD NEW, or --dir DIR, or NEW --baseline B")
        if args.baseline:
            old_path = args.baseline
        old_recs, old_env = load_records(old_path)
        new_recs, new_env = load_records(new_path)
    except OSError as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    old_idx, new_idx = index(old_recs), index(new_recs)
    if not old_idx or not new_idx:
        print(f"bench_diff: no metric records in "
              f"{old_path if not old_idx else new_path}",
              file=sys.stderr)
        return 2
    regs, imps, infos = diff(old_idx, new_idx, args.threshold)
    warns = _env_mismatch(old_env, new_env)

    if args.json:
        print(json.dumps({
            "old": old_path, "new": new_path,
            "threshold": args.threshold,
            "regressions": regs, "improvements": imps,
            "unchanged_or_unclassified": len(infos),
            "env_mismatch": warns,
            "verdict": "regressed" if regs else "clean"}))
    else:
        print(f"bench_diff: {os.path.basename(old_path)} -> "
              f"{os.path.basename(new_path)}  "
              f"({len(set(old_idx) & set(new_idx))} shared metrics, "
              f"threshold {args.threshold:.0%})")
        for w in warns:
            print(f"  WARNING env mismatch — {w} (comparison may be "
                  f"unsound)")
        if regs:
            print(f"REGRESSIONS ({len(regs)}):")
            for r in regs:
                print(_fmt(r))
        if imps:
            print(f"improvements ({len(imps)}):")
            for r in imps:
                print(_fmt(r))
        if not regs and not imps:
            print("  no change past threshold")
        only_old = sorted(set(old_idx) - set(new_idx))
        only_new = sorted(set(new_idx) - set(old_idx))
        if only_old:
            print(f"  dropped metrics: {', '.join(only_old[:8])}"
                  + (" ..." if len(only_old) > 8 else ""))
        if only_new:
            print(f"  new metrics: {', '.join(only_new[:8])}"
                  + (" ..." if len(only_new) > 8 else ""))
        print(f"verdict: {'REGRESSED' if regs else 'clean'}")
    return 1 if regs else 0


if __name__ == "__main__":
    sys.exit(main())
