#!/usr/bin/env python
"""Open-loop load generator for paddle_tpu.serving.

Drives a :class:`paddle_tpu.serving.Server` (in-process toy model by
default, or a remote HTTP endpoint via ``--url``) with Poisson arrivals
at ``--rate`` req/s and reports the serving-latency metrics PERF.md
defines:

- **TTFT** (time to first token): submit → first generated token at the
  client. Queueing + admission prefill + the first decode-segment share.
- **TPOT** (time per output token): (last token - first token) /
  (n_tokens - 1) per request — the steady decode cadence a streaming
  client observes.
- **throughput**: total generated tokens / wall time of the whole run.

OPEN loop: arrival times are drawn up front from the Poisson process
and each request is submitted at its scheduled time regardless of how
many are still in flight — closed-loop generators (wait-for-completion)
hide queueing collapse, which is exactly what the backpressure path
must be measured under. Rejected submissions (queue full) are counted,
not retried.

Usage::

    python tools/serve_bench.py --rate 16 --requests 64
    python tools/serve_bench.py --url http://127.0.0.1:8000 --rate 8
    python tools/serve_bench.py --monitor-out run.jsonl   # + monitor dump
    # bucketing A/B (PERF.md prefill-cost methodology): lognormal
    # prompt mix, report compiled prefill programs alongside TTFT/TPOT
    python tools/serve_bench.py --prompt-dist lognormal --prompt-len 4:96 \
        --warmup --prefill-chunk 32
    python tools/serve_bench.py --prompt-dist lognormal --prompt-len 4:96 \
        --prefill-buckets none
    # chaos soak (in-process only): inject seeded faults at the named
    # serving seams and report survival/restart/recovery numbers — the
    # fault-isolation acceptance run (README "Failure modes & recovery")
    python tools/serve_bench.py --fault-rate 0.1 --fault-site decode \
        --fault-kind engine --max-restarts 100
    # KV memory-pressure A/B (PERF.md utilization/throughput
    # methodology): same pool, reserved vs optimistic admission —
    # compare throughput + occupancy p50/p99 against the preemption
    # count and the preempted-request latency penalty
    python tools/serve_bench.py --num-pages 24 --admission-mode reserved
    python tools/serve_bench.py --num-pages 24 --admission-mode optimistic \
        --kv-watermark 0.9 --max-preemptions 10
    # automatic prefix caching A/B (PERF.md prefix-caching
    # methodology): every request shares a 64-token system prompt —
    # compare TTFT p50/p99, serve_kv_occupancy, and
    # serve_prefix_hit_rate / serve_prefill_tokens_saved across the
    # two runs
    python tools/serve_bench.py --shared-prefix-len 64 --cache-prefixes off
    python tools/serve_bench.py --shared-prefix-len 64 --cache-prefixes on
    # speculative-decoding A/B (PERF.md spec-serving methodology):
    # repetitive prompts (the accepting case) through the SAME load
    # three times — plain, host-mode spec, device-mode spec —
    # reporting serve_tpot_*_{plain,spec,specdev}, tokens/forward,
    # acceptance, serve_spec_host_syncs_per_token (0.0 on the device
    # arm) and serve_spec_mode_tpot_speedup (host/device)
    python tools/serve_bench.py --spec-ab --draft-k 6 --repeat-unit 4 \
        --prompt-len 16:24 --max-new 24 --warmup
    # fleet survival A/B (PERF.md fleet-survival methodology): the SAME
    # load + fault plan (kill replica 0 at t=2s) through 1 replica vs 3
    # — read serve_fleet_survival_rate, serve_failover_count,
    # serve_failover_latency_p99, serve_breaker_opens across the runs
    python tools/serve_bench.py --router --replicas 1 --kill-replica-at 2
    python tools/serve_bench.py --router --replicas 3 --kill-replica-at 2
    # cross-process fleet A/B (PERF.md cross-process-fleet
    # methodology): the SAME load
    # through one equal-silicon in-process server (2x pages/batch/
    # queue) vs a Router over 2 replica SUBPROCESSES speaking HTTP —
    # read serve_fleet_ttft_overhead / serve_fleet_tpot_overhead /
    # serve_fleet_throughput_ratio; add --kill-replica-at to SIGKILL a
    # replica process mid-run and watch failover replay + respawn
    python tools/serve_bench.py --fleet 2 --warmup
    python tools/serve_bench.py --fleet 2 --kill-replica-at 2
    # request-lifecycle tracing (PERF.md tracing methodology): capture
    # a Chrome-trace/Perfetto file of the whole run and report the
    # trace-derived TTFT decomposition (queue vs prefill vs gap share)
    python tools/serve_bench.py --trace-out /tmp/serve_trace.json --warmup
    # tracing-overhead A/B: IDENTICAL load twice — trace off then on —
    # reporting serve_tpot_* per arm plus serve_trace_tpot_overhead
    # (the "near-zero when disabled / cheap when on" claim, measured)
    python tools/serve_bench.py --trace-ab --warmup
    # quantized-KV A/B (PERF.md quantized-KV methodology): IDENTICAL
    # load through bf16 pools vs int8 pools AT EQUAL HBM (the int8 arm
    # gets 2x --num-pages) — compare serve_kv_occupancy_* (halved at
    # matched load = doubled capacity), serve_kv_quant_tpot_speedup,
    # serve_kv_quant_capacity_ratio, and the bounded-numerics records
    # serve_kv_quant_max_logit_div / serve_kv_quant_token_flips
    python tools/serve_bench.py --kv-ab --warmup
    python tools/serve_bench.py --kv-dtype int8   # single int8 run
    # multi-tenant LoRA (PERF.md multi-tenant-LoRA methodology): K
    # synthetic adapters hot-loaded into the engine's device bank,
    # each request drawn to one (uniform or zipf) — read
    # serve_lora_adapters_resident / serve_lora_mix_entropy, and A/B
    # the SAME pre-drawn load base-vs-LoRA for the per-token cost of
    # the batched-adapter gather (serve_lora_tpot_overhead)
    python tools/serve_bench.py --adapters 8 --adapter-dist zipf --warmup
    python tools/serve_bench.py --lora-ab --warmup   # K=0 vs K=8
    python tools/serve_bench.py --adapters 4 --tenant-quotas 2  # quotas

    # SLO/goodput capture (PERF.md SLO methodology): arm an SLOPolicy,
    # read serve_goodput + the digest-exact serve_slo_ttft_p99 /
    # serve_slo_tpot_p99 (per-tenant table on stdout; GET /stats is
    # the live equivalent) — and the off-vs-on recording overhead A/B
    python tools/serve_bench.py --slo-ttft 0.5 --slo-tpot 0.05 \
        --adapters 4 --adapter-dist zipf --warmup
    python tools/serve_bench.py --slo-ab --warmup

Output: one human table plus BENCH-shaped JSON records
(``{"metric": ..., "value": ..., "unit": ...}``) on stdout. Chaos runs
add ``serve_faults_injected`` / ``serve_requests_survived`` /
``serve_requests_failed`` / ``serve_restarts`` /
``serve_recovery_p{50,90,99}``.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

# runnable as `python tools/serve_bench.py` from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _percentile(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.ttft = []
        self.tpot = []
        self.e2e = []
        self.e2e_preempted = []   # e2e of requests preempted >= once
        #                           (in-process mode only) — the
        #                           preemption latency penalty is the
        #                           mean gap vs the unpreempted ones
        self.e2e_failover = []    # e2e of requests that failed over to
        #                           another replica (--router mode) —
        #                           serve_failover_latency_p99 is the
        #                           tail a migrated request pays
        self.tokens = 0
        self.rejected = 0
        self.failed = 0
        self.shed = 0             # rejections with reason "shed" (the
        #                           burn-rate door, --overload-ab's
        #                           ctrlon arm) — a subset of rejected

    def record(self, ttft, tpot, e2e, n_tokens, preempted=False,
               failover=False):
        with self.lock:
            if ttft is not None:
                self.ttft.append(ttft)
            if tpot is not None:
                self.tpot.append(tpot)
            self.e2e.append(e2e)
            if preempted:
                self.e2e_preempted.append(e2e)
            if failover:
                self.e2e_failover.append(e2e)
            self.tokens += n_tokens

    def reject(self, shed=False):
        with self.lock:
            self.rejected += 1
            if shed:
                self.shed += 1

    def fail(self):
        with self.lock:
            self.failed += 1


def _drive_inproc(server, prompt, cfg, stats, tenant=None):
    from paddle_tpu.serving import RequestRejected

    t0 = time.monotonic()
    try:
        handle = server.submit(prompt, cfg, tenant=tenant)
    except RequestRejected as e:
        stats.reject(shed=getattr(e, "reason", None) == "shed")
        return
    first = last = None
    n = 0
    try:
        for _tok in handle.stream(timeout=120):
            now = time.monotonic()
            if first is None:
                first = now
            last = now
            n += 1
    except Exception:
        stats.fail()
        return
    if handle.status != "finished":
        stats.fail()
        return
    end = time.monotonic()
    stats.record(None if first is None else first - t0,
                 None if (n < 2 or first is None) else (last - first)
                 / (n - 1),
                 end - t0, n,
                 preempted=getattr(handle, "_preempts", 0) > 0,
                 failover=getattr(handle, "_failovers", 0) > 0)


def _drive_http(url, prompt, cfg_body, stats):
    import http.client
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    t0 = time.monotonic()
    try:
        conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                          timeout=120)
        body = dict(cfg_body)
        body["prompt"] = [int(t) for t in prompt]
        body["stream"] = True
        conn.request("POST", "/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status == 429 or resp.status == 503:
            stats.reject()
            return
        if resp.status != 200:
            stats.fail()
            return
        first = last = None
        n = 0
        ok = False
        while True:
            line = resp.readline()
            if not line:
                break
            rec = json.loads(line)
            if "token" in rec:
                now = time.monotonic()
                if first is None:
                    first = now
                last = now
                n += 1
            elif rec.get("done"):
                ok = rec.get("status") == "finished"
        conn.close()
    except Exception:
        stats.fail()
        return
    if not ok:
        stats.fail()
        return
    end = time.monotonic()
    stats.record(None if first is None else first - t0,
                 None if (n < 2 or first is None) else (last - first)
                 / (n - 1),
                 end - t0, n)


# the in-process toy preset's vocab: prompts are drawn BEFORE any
# server exists (so A/B arms replay identical load), and _run_arm
# asserts this against the model the server was actually built with —
# a drifting preset must fail loudly, not clamp token ids silently
_TOY_VOCAB = 256


def _toy_engine(args, speculative: bool = False):
    """Build one seeded toy engine from the CLI knobs — the ONE place
    the engine kwargs live, shared by the single-server and router
    builders (a knob added to one mode must not silently benchmark a
    differently-configured engine in the other). Returns
    (engine, vocab_size)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.generation import (
        PagedContinuousBatchingEngine)
    from paddle_tpu.models import LlamaForCausalLM, llama_config

    paddle.seed(0)
    cfg = llama_config(getattr(args, "preset", "tiny"),
                       num_hidden_layers=args.layers)
    model = LlamaForCausalLM(cfg)
    if args.prefill_buckets == "auto":
        buckets = "auto"
    elif args.prefill_buckets in ("none", "off"):
        buckets = None
    else:
        buckets = [int(x) for x in args.prefill_buckets.split(",")]
    eng = PagedContinuousBatchingEngine(
        model, max_batch=args.max_batch, num_pages=args.num_pages,
        page_size=args.page_size, max_pages=args.max_pages,
        prefill_buckets=buckets, prefill_chunk=args.prefill_chunk,
        admission_mode=args.admission_mode,
        kv_watermark=args.kv_watermark,
        prefix_cache=(args.cache_prefixes == "on"),
        kv_dtype=args.kv_dtype,
        draft_k=(args.draft_k if speculative else 0),
        spec_mode=getattr(args, "spec_mode", "host"),
        lora_capacity=args.adapters,
        lora_rank=args.lora_rank,
        lora_targets=tuple(t.strip()
                           for t in args.lora_targets.split(",")
                           if t.strip()),
        tp_degree=getattr(args, "tp", 1))
    return eng, cfg.vocab_size


def _toy_server_kwargs(args, max_restarts=None):
    """Server knobs from the CLI — shared by both builders."""
    slo_policy = None
    if (getattr(args, "slo_ttft", None) is not None
            or getattr(args, "slo_tpot", None) is not None):
        from paddle_tpu.monitor.slo import SLOPolicy

        slo_policy = SLOPolicy(ttft_p99_s=args.slo_ttft,
                               tpot_p99_s=args.slo_tpot)
    control_policy = None
    if getattr(args, "control_on", False):
        from paddle_tpu.serving import ControlPolicy

        # the ctrlon arm's plane: default ladder/shed thresholds, but
        # (a) shed_min_count scaled so only the HOT tenant (60% of the
        # mix) accumulates enough scored requests in the fast window
        # to shed — the thin-tenant guard keeps the 10% cold tenants
        # un-shed by construction (requests//8 sits between one cold
        # tenant's ~10% share and the hot tenant's 60%) — and (b) a
        # fast tick + short dwell so the plane reacts within a
        # seconds-long bench run
        control_policy = ControlPolicy(
            shed_min_count=max(8, args.requests // 8),
            tick_interval_s=0.1,
            rung_dwell_s=1.0)
    return dict(
        max_queue=args.max_queue, segment_steps=args.segment_steps,
        warmup=args.warmup,
        max_restarts=(args.max_restarts if max_restarts is None
                      else max_restarts),
        max_replays=args.max_replays,
        max_preemptions=args.max_preemptions,
        restart_backoff_s=args.restart_backoff,
        stall_timeout_s=args.stall_timeout,
        tenant_quotas=args.tenant_quotas,
        slo_policy=slo_policy,
        control_policy=control_policy)


def _build_toy_server(args, speculative: bool = False):
    from paddle_tpu.serving import Server

    eng, vocab = _toy_engine(args, speculative)
    plan = None
    if args.fault_rate > 0:
        from paddle_tpu.inference.generation import EngineFault
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        plan = FaultPlan()
        sites = [s.strip() for s in args.fault_site.split(",")
                 if s.strip()]
        if args.fault_kind == "request":
            from paddle_tpu.inference.generation import REQUEST_SITES
            batch_wide = [s for s in sites if s not in REQUEST_SITES]
            if batch_wide:
                # the scheduler escalates EVERY non-fatal fault at a
                # batch-wide seam to engine recovery (no single request
                # to pin it on) — a "request-kind" run there would
                # silently measure restarts, not containment
                print("warning: --fault-kind request at batch-wide "
                      f"site(s) {batch_wide} is escalated to engine "
                      "recovery; use admit/prefill/chunk to measure "
                      "per-request containment", file=sys.stderr)
        # engine-kind faults drive the supervised-recovery path;
        # request-kind ones (site-default classification) drive
        # per-request containment. A FACTORY, not an instance: every
        # injection over a long soak must raise a fresh exception
        exc = ((lambda: EngineFault("injected chaos fault"))
               if args.fault_kind == "engine" else None)
        plan.random_raises(sites, args.fault_rate, seed=args.seed,
                           exc=exc)
        eng = FaultyEngine(eng, plan)
    srv = Server(eng, speculative=speculative,
                 **_toy_server_kwargs(args))
    srv.wait_ready()   # warmup compiles are NOT part of the measured run
    return srv, vocab, plan


def _build_toy_router(args):
    """Fleet mode (--replicas N / --router): a Router over N in-process
    replica Servers built from one ReplicaSpec. Each replica gets its
    OWN seeded model (deterministic init -> bitwise-identical weights
    across the fleet, the property greedy failover parity rides on).
    With --kill-replica-at T, the FIRST build of replica 0 is wrapped
    in a FaultyEngine whose plan the timer kills mid-run; the
    supervisor's rebuild comes up clean. Returns
    (router, vocab, kill_fn)."""
    from paddle_tpu.serving import ReplicaSpec, Router
    from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

    kill_plan = FaultPlan()
    builds = {"n": 0}
    vocab = {}

    def factory():
        i = builds["n"]
        builds["n"] += 1
        eng, vocab["size"] = _toy_engine(args)
        if i == 0 and args.kill_replica_at is not None:
            return FaultyEngine(eng, kill_plan)
        return eng

    spec = ReplicaSpec(factory, server_kwargs=_toy_server_kwargs(
        args,
        # a killed replica must DIE (the router absorbs it), not spin
        # its own restart budget against a permanent fault plan
        max_restarts=(0 if args.kill_replica_at is not None
                      else None)))
    router = Router(spec, replicas=args.replicas,
                    max_failovers=args.max_failovers,
                    breaker_threshold=args.breaker_threshold,
                    replica_backoff_s=args.replica_backoff,
                    monitor_interval_s=0.05)
    router.wait_ready()

    fired = {"kill": False}

    def kill_fn():
        fired["kill"] = True
        print(f"[chaos] killing replica 0 at t="
              f"{args.kill_replica_at}s", file=sys.stderr)
        kill_plan.kill("decode")

    kill_fn.fired = fired
    return router, vocab["size"], (
        kill_fn if args.kill_replica_at is not None else None)


def _build_fleet_router(args):
    """Cross-process fleet mode (--fleet N): a Router over N replica
    SUBPROCESSES (``python -m paddle_tpu.serving.remote``), each one
    an independently seeded engine at the base CLI knobs — the same
    deterministic-init property the in-process fleet rides on, so
    greedy failover replay stays bitwise-identical across processes.
    Only the knobs the replica entrypoint exposes are forwarded (main
    validates the rest are at defaults). With --kill-replica-at T, the
    timer SIGKILLs replica 0's process; the supervisor respawns it.
    Returns (router, vocab, kill_fn)."""
    from paddle_tpu.models import llama_config
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.remote import RemoteReplicaSpec

    child = ["--preset", args.preset, "--layers", str(args.layers),
             "--max-batch", str(args.max_batch),
             "--num-pages", str(args.num_pages),
             "--page-size", str(args.page_size),
             "--max-pages", str(args.max_pages),
             "--kv-dtype", args.kv_dtype,
             "--max-queue", str(args.max_queue),
             "--segment-steps", str(args.segment_steps),
             "--prefix-cache", args.cache_prefixes,
             "--warmup", "on" if args.warmup else "off"]
    if args.prefill_chunk is not None:
        child += ["--prefill-chunk", str(args.prefill_chunk)]
    if args.slo_ttft is not None:
        child += ["--slo-ttft", str(args.slo_ttft)]
    if args.slo_tpot is not None:
        child += ["--slo-tpot", str(args.slo_tpot)]
    spec = RemoteReplicaSpec(
        args=child,
        # the children record their own SLO digests; the router MERGES
        # them over the wire — the serve_goodput/serve_slo_* records
        # below are fleet-exact, not averaged
        env={"FLAGS_enable_monitor": "1"})
    router = Router(spec, replicas=args.fleet,
                    max_failovers=args.max_failovers,
                    breaker_threshold=args.breaker_threshold,
                    replica_backoff_s=args.replica_backoff,
                    monitor_interval_s=0.05)
    router.wait_ready(timeout=240.0)

    fired = {"kill": False}

    def kill_fn():
        fired["kill"] = True
        print(f"[chaos] SIGKILL replica 0 process at t="
              f"{args.kill_replica_at}s", file=sys.stderr)
        victim = router._replicas[0].server
        if getattr(victim, "proc", None) is not None:
            victim.proc.kill()

    kill_fn.fired = fired
    vocab = llama_config(args.preset, num_hidden_layers=1).vocab_size
    return router, vocab, (
        kill_fn if args.kill_replica_at is not None else None)


def _draw_len(rng, dist: str, lo: int, hi: int) -> int:
    """One prompt length from the configured distribution. lognormal is
    the realistic serving shape (many short, a long tail) — the mix that
    exposes per-length prefill recompiles, which uniform draws over a
    narrow range can hide."""
    if dist == "lognormal":
        import math

        mu = (math.log(lo) + math.log(hi)) / 2.0
        sigma = max((math.log(hi) - math.log(lo)) / 4.0, 1e-6)
        return min(hi, max(lo, int(round(rng.lognormvariate(mu, sigma)))))
    return rng.randint(lo, hi)


def _prefill_program_stats():
    """Compiled-prefill-program counts + compile seconds from the live
    monitor registry (in-process mode): the bucketing win in numbers."""
    from paddle_tpu import monitor

    snap = monitor.snapshot()["metrics"]

    def by_fn(name):
        # sum per entry point: the counters carry ("fn", "program")
        # since the ledger split, and one fn compiles many programs
        out = {}
        for s in snap.get(name, {}).get("samples", []):
            fn = s["labels"]["fn"]
            out[fn] = out.get(fn, 0.0) + s["value"]
        return out

    misses = by_fn("paddle_tpu_jit_cache_miss_total")
    secs = by_fn("paddle_tpu_jit_compile_seconds_total")
    prefill_fns = ("cb_prefill", "cb_prefill_chunk")
    return (sum(int(misses.get(f, 0)) for f in prefill_fns),
            sum(secs.get(f, 0.0) for f in prefill_fns),
            sum(int(v) for v in misses.values()),
            sum(secs.values()))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="HTTP endpoint (default: in-process toy model)")
    ap.add_argument("--rate", type=float, default=8.0,
                    help="mean arrival rate, requests/s (Poisson)")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--prompt-len", default="4:24", metavar="LO:HI",
                    help="prompt-length range")
    ap.add_argument("--prompt-dist", choices=("uniform", "lognormal"),
                    default="uniform",
                    help="prompt-length distribution over LO:HI "
                         "(lognormal = realistic many-short/long-tail "
                         "serving mix)")
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    # in-process toy engine knobs
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--preset", default="tiny",
                    help="llama_config preset for the in-process toy "
                         "engine (tiny default; 13b/65b are the "
                         "memory-fit configs a TP mesh exists to "
                         "serve — MEMORY_CONFIG3.json)")
    ap.add_argument("--tp", type=int, default=1, metavar="N",
                    help="tensor-parallel degree of the in-process "
                         "engine: weights + KV pools shard over an "
                         "N-device 'mp' mesh (CPU CI: force devices "
                         "with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--tp-ab", action="store_true",
                    help="A/B mode: run the SAME pre-drawn load "
                         "through a TP=1 engine then a TP=N engine "
                         "(N from --tp, default 2) and report "
                         "serve_tp_tpot_speedup + "
                         "serve_tp_max_model_bytes (the HBM capacity "
                         "a TP=N mesh adds at fixed per-chip memory)")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--num-pages", type=int, default=48)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-pages", type=int, default=16)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--segment-steps", type=int, default=4)
    ap.add_argument("--prefill-buckets", default="auto",
                    metavar="auto|none|N,N,...",
                    help="prefill length buckets ('none' = exact-length "
                         "prefill, one compile per distinct length)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked-prefill chunk size (tokens); prompts "
                         "longer than this admit one chunk per gap")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile all prefill buckets + the segment "
                         "program before the measured run")
    # KV memory-pressure knobs (paged engine admission policy)
    ap.add_argument("--admission-mode", choices=("reserved",
                                                 "optimistic"),
                    default="reserved",
                    help="page-pool admission policy: reserved = "
                         "worst-case pages claimed up front (safe, "
                         "caps concurrency); optimistic = prompt + "
                         "one page, grow per gap, preempt-and-replay "
                         "under pressure (vLLM-style)")
    ap.add_argument("--kv-watermark", type=float, default=0.9,
                    help="optimistic mode: pause NEW admissions while "
                         "pool occupancy would exceed this fraction "
                         "(preemption stays the fallback, not the "
                         "steady state)")
    ap.add_argument("--max-preemptions", type=int, default=5,
                    help="memory-pressure preemptions one request may "
                         "absorb before it fails with "
                         "PreemptionBudgetExceeded")
    # prefix-cache A/B knobs (PERF.md prefix-caching methodology)
    ap.add_argument("--shared-prefix-len", type=int, default=0,
                    metavar="N",
                    help="prepend the SAME N seeded tokens to every "
                         "prompt (a shared system prompt); the "
                         "per-request tail still draws from "
                         "--prompt-len. A/B this against "
                         "--cache-prefixes on|off")
    ap.add_argument("--cache-prefixes", choices=("on", "off"),
                    default="off",
                    help="enable the paged engine's automatic prefix "
                         "cache (refcounted copy-on-write shared KV "
                         "pages): warm admissions map resident prompt "
                         "blocks instead of re-prefilling them")
    # speculative-decoding knobs (in-process mode; PERF.md spec-serving
    # methodology)
    ap.add_argument("--speculative", choices=("on", "off"),
                    default="off",
                    help="serve every greedy request speculatively "
                         "(per-slot n-gram proposers verified inside "
                         "the one widened decode-segment program)")
    ap.add_argument("--draft-k", type=int, default=6,
                    help="draft window (tokens proposed per verify "
                         "forward) when speculation is on")
    ap.add_argument("--spec-mode", choices=("host", "device"),
                    default="host",
                    help="where drafts come from when speculation is "
                         "on: 'host' round-trips the n-gram proposer "
                         "every verify step, 'device' runs the fused "
                         "propose+verify+accept segment program (one "
                         "host readback per SEGMENT)")
    ap.add_argument("--spec-ab", action="store_true",
                    help="A/B mode: run the SAME load three times — "
                         "plain, host-mode speculative, device-mode "
                         "speculative — and report serve_tpot_* per "
                         "arm plus the spec and host/device speedup "
                         "ratios")
    ap.add_argument("--repeat-unit", type=int, default=0, metavar="N",
                    help="build each prompt by tiling a seeded N-token "
                         "unit (self-repetitive text — the n-gram "
                         "proposer's accepting case; 0 = fully random "
                         "prompts, the adversarial floor)")
    # fleet knobs (--replicas N routes through paddle_tpu.serving.Router;
    # PERF.md fleet-survival methodology)
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica Servers behind a health-aware "
                         "Router (>1 implies --router)")
    ap.add_argument("--router", action="store_true",
                    help="route through a Router even with 1 replica "
                         "(measures the router's own overhead + the "
                         "no-spare-capacity fault baseline)")
    ap.add_argument("--kill-replica-at", type=float, default=None,
                    metavar="T",
                    help="kill replica 0 (permanent engine faults) T "
                         "seconds into the measured run; its requests "
                         "fail over, the supervisor rebuilds it "
                         "(--fleet mode: SIGKILLs the replica "
                         "PROCESS; the supervisor respawns it)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="cross-process A/B: run the SAME pre-drawn "
                         "load through (a) ONE in-process server with "
                         "N x --num-pages / N x --max-batch / N x "
                         "--max-queue (the equal-chip monolithic "
                         "baseline) then (b) a Router over N replica "
                         "SUBPROCESSES (paddle_tpu.serving.remote, "
                         "one engine each at the base knobs) — "
                         "reports per-arm serve_ttft/tpot/throughput "
                         "plus serve_fleet_* ratios, the price of the "
                         "HTTP hop + fan-out at equal silicon")
    ap.add_argument("--max-failovers", type=int, default=3,
                    help="replica migrations one request may survive "
                         "before FailoverBudgetExceeded")
    ap.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive failures before a replica's "
                         "circuit breaker opens")
    ap.add_argument("--replica-backoff", type=float, default=0.25,
                    help="base of the supervisor's exponential "
                         "replica-restart backoff (s)")
    # chaos knobs (in-process mode only; paddle_tpu.testing.faults)
    ap.add_argument("--fault-rate", type=float, default=0.0,
                    help="seeded per-call fault probability at each "
                         "--fault-site seam (0 = chaos off)")
    ap.add_argument("--fault-site", default="decode",
                    metavar="SITE[,SITE...]",
                    help="injection seams: admit, prefill, chunk, "
                         "decode, collect")
    ap.add_argument("--fault-kind", choices=("request", "engine"),
                    default="engine",
                    help="engine = EngineFault (supervised restart + "
                         "replay); request = site-default "
                         "classification (per-request containment at "
                         "admission seams)")
    ap.add_argument("--max-restarts", type=int, default=8,
                    help="server lifetime engine-restart budget")
    ap.add_argument("--max-replays", type=int, default=8,
                    help="per-request replay budget across restarts "
                         "(the Server default of 2 would fail "
                         "long-lived requests on a long soak and "
                         "corrupt the survival numbers)")
    ap.add_argument("--restart-backoff", type=float, default=0.01,
                    help="base of the exponential restart backoff (s)")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    help="arm the stall watchdog (s; default off)")
    ap.add_argument("--monitor-out", default=None, metavar="JSONL",
                    help="also dump the in-process monitor registry "
                         "(in-process mode only)")
    # request-lifecycle tracing knobs (paddle_tpu.tracing; in-process)
    ap.add_argument("--trace-out", default=None, metavar="JSON",
                    help="enable FLAGS_enable_trace for the run and "
                         "write the Chrome-trace/Perfetto JSON of the "
                         "whole run here (also reports the "
                         "trace-derived TTFT decomposition records)")
    ap.add_argument("--trace-ab", action="store_true",
                    help="A/B mode: run the SAME load twice — tracing "
                         "off then on — and report serve_tpot_* per "
                         "arm plus serve_trace_tpot_overhead (the "
                         "tracing-overhead record PERF.md quotes)")
    # quantized-KV knobs (paged engine int8 pages, quantization.kv)
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"),
                    default="bf16",
                    help="KV page storage dtype: int8 halves decode "
                         "read bytes and doubles pages at fixed HBM "
                         "(bounded, not bitwise, numerics)")
    ap.add_argument("--kv-ab", action="store_true",
                    help="A/B mode: run the SAME load twice — bf16 "
                         "pools, then int8 pools with --num-pages "
                         "DOUBLED (equal HBM) — and report per-arm "
                         "records plus serve_kv_quant_tpot_speedup, "
                         "serve_kv_quant_capacity_ratio and the "
                         "bounded-numerics divergence probe")
    # multi-tenant LoRA knobs (in-process single-server mode;
    # paddle_tpu.serving.adapters)
    ap.add_argument("--adapters", type=int, default=0, metavar="K",
                    help="hot-load K seeded synthetic LoRA adapters "
                         "and draw every request's adapter from them "
                         "(0 = base model only)")
    ap.add_argument("--adapter-dist", choices=("uniform", "zipf"),
                    default="uniform",
                    help="per-request adapter draw: uniform, or zipf "
                         "(s=1.1 — the realistic many-tenants shape: "
                         "a few hot fine-tunes, a long cold tail)")
    ap.add_argument("--lora-rank", type=int, default=4,
                    help="bank rank of the synthetic adapters")
    ap.add_argument("--lora-targets", default="q,v",
                    help="comma-separated LoRA target projections "
                         "(subset of q,k,v,o,gate,up,down)")
    ap.add_argument("--tenant-quotas", type=int, default=None,
                    metavar="N",
                    help="cap every tenant (= adapter) at N "
                         "concurrently admitted requests; a tenant "
                         "over quota defers without starving others")
    # SLO/goodput knobs (paddle_tpu.monitor.slo; in-process modes)
    ap.add_argument("--slo-ttft", type=float, default=None,
                    metavar="S",
                    help="per-request TTFT SLO threshold (s): arms an "
                         "SLOPolicy on the server(s) and reports "
                         "serve_goodput + the digest-exact "
                         "serve_slo_ttft_p99/serve_slo_tpot_p99")
    ap.add_argument("--slo-tpot", type=float, default=None,
                    metavar="S",
                    help="per-request TPOT SLO threshold (s); see "
                         "--slo-ttft")
    ap.add_argument("--slo-ab", action="store_true",
                    help="A/B mode: run the SAME pre-drawn load twice "
                         "— monitor+SLO recording OFF, then ON with "
                         "the --slo-ttft/--slo-tpot policy (defaults "
                         "1.0/0.25 s if unset) — and report "
                         "serve_slo_tpot_overhead (the PR 8 bar: "
                         "<= 1.02x, near-zero when off)")
    ap.add_argument("--lora-ab", action="store_true",
                    help="A/B mode: run the SAME pre-drawn load twice "
                         "— base model (K=0) then K adapters (default "
                         "8) — and report serve_lora_tpot_overhead "
                         "(the per-token price of the batched-adapter "
                         "gather)")
    # program-ledger knobs (paddle_tpu.monitor.ledger; in-process)
    ap.add_argument("--profile", action="store_true",
                    help="enable the program ledger "
                         "(FLAGS_enable_ledger) for the run and print "
                         "the per-program roofline table (dispatches, "
                         "compiles, FLOPs, MFU, memory/compute-bound "
                         "verdict) after the load drains")
    ap.add_argument("--profile-out", default=None, metavar="PATH",
                    help="also write the raw /profile JSON snapshot "
                         "(the Server.profile() shard) to PATH — feed "
                         "it to tools/monitor_report.py --profile or "
                         "archive it next to the BENCH records")
    ap.add_argument("--profile-ab", action="store_true",
                    help="A/B mode: run the SAME pre-drawn load twice "
                         "— ledger OFF, then ON — and report "
                         "serve_profile_tpot_overhead (the PR 15 "
                         "one-bool-branch bar: <= 1.05x)")
    # overload control plane knobs (paddle_tpu.serving.control)
    ap.add_argument("--overload-ab", action="store_true",
                    help="A/B mode: three arms on pre-drawn load with "
                         "a 60%%-hot tenant mix — 'cap' at --rate (the "
                         "at-capacity baseline), then 'ctrloff'/"
                         "'ctrlon' replaying the IDENTICAL load at "
                         "--overload-factor x that rate without/with "
                         "the SLO-driven control plane "
                         "(Server(control_policy=...)) — and report "
                         "serve_goodput_* per arm plus the cold-"
                         "tenant goodput retention verdict (the "
                         "overload bar: ctrlon cold goodput within "
                         "10%% of cap while the hot tenant sheds)")
    ap.add_argument("--overload-factor", type=float, default=2.0,
                    metavar="X",
                    help="overload multiple for the ctrloff/ctrlon "
                         "arms: arrival times are the cap arm's "
                         "schedule compressed by X (> 1; default 2.0)")
    ap.add_argument("--wire-chaos", action="store_true",
                    help="A/B mode over the REAL wire (serve_http + "
                         "RemoteReplica): 'wireclean' drives the "
                         "pre-drawn load unfaulted, 'wirechaos' "
                         "replays it through injected delay/drop/"
                         "half-close/corrupt at the generate and "
                         "kv_import seams — reports serve_wire_"
                         "resumes/failovers/reships/integrity_rejects"
                         "/survival_rate and the bitwise token-parity "
                         "verdict (a flaky network degrades latency, "
                         "never correctness)")
    args = ap.parse_args(argv)

    rng = random.Random(args.seed)
    lo, hi = (int(x) for x in args.prompt_len.split(":"))
    if args.url is not None and (args.fault_rate > 0 or args.spec_ab
                                 or args.speculative == "on"
                                 or args.trace_out or args.trace_ab
                                 or args.slo_ab
                                 or args.slo_ttft is not None
                                 or args.slo_tpot is not None):
        print("--fault-rate/--speculative/--spec-ab/--trace-out/"
              "--trace-ab/--slo-* need the in-process engine "
              "(no --url)", file=sys.stderr)
        return 2
    if sum([args.spec_ab, args.trace_ab, args.kv_ab,
            args.lora_ab, args.tp_ab, args.slo_ab,
            args.profile_ab, args.overload_ab,
            args.wire_chaos]) > 1:
        print("--spec-ab/--trace-ab/--kv-ab/--lora-ab/--tp-ab/--slo-ab/"
              "--profile-ab/--overload-ab/--wire-chaos are separate "
              "A/Bs; run them one at a time", file=sys.stderr)
        return 2
    if args.wire_chaos and (args.url is not None or args.router
                            or args.replicas > 1 or args.fleet
                            or args.fault_rate > 0):
        print("--wire-chaos builds its own wire (in-process servers "
              "behind serve_http); it composes with neither --url "
              "nor --router/--replicas/--fleet/--fault-rate",
              file=sys.stderr)
        return 2
    if (args.profile or args.profile_ab) and args.url is not None:
        print("--profile/--profile-ab need the in-process engine "
              "(no --url)", file=sys.stderr)
        return 2
    if args.slo_ab and args.slo_ttft is None and args.slo_tpot is None:
        # the on arm needs thresholds to score against; generous
        # defaults keep the A/B about RECORDING cost, not miss churn
        args.slo_ttft, args.slo_tpot = 1.0, 0.25
    if args.overload_ab:
        if (args.url is not None or args.router or args.replicas > 1
                or args.fleet):
            print("--overload-ab needs the single in-process engine "
                  "(no --url, no --router/--replicas/--fleet)",
                  file=sys.stderr)
            return 2
        if args.overload_factor <= 1.0:
            print("--overload-factor must be > 1", file=sys.stderr)
            return 2
        if args.slo_ttft is None and args.slo_tpot is None:
            # goodput/burn need a policy; TTFT is the queue-sensitive
            # dimension overload actually moves — TPOT stays off so a
            # big-batch cap arm does not pollute the baseline
            args.slo_ttft = 1.0
    if args.tp < 1:
        print("--tp must be >= 1", file=sys.stderr)
        return 2
    if args.tp_ab and (args.url is not None or args.router
                       or args.replicas > 1):
        print("--tp-ab needs the single in-process engine (no --url, "
              "no --router/--replicas)", file=sys.stderr)
        return 2
    if args.kv_ab and (args.url is not None or args.router
                       or args.replicas > 1):
        print("--kv-ab needs the single in-process engine (no --url, "
              "no --router/--replicas)", file=sys.stderr)
        return 2
    if args.replicas < 1:
        print("--replicas must be >= 1", file=sys.stderr)
        return 2
    if args.fleet < 0:
        print("--fleet must be >= 1 (0 = off)", file=sys.stderr)
        return 2
    if args.fleet:
        # the fleet arm's engines live in CHILD processes: the local
        # chaos/trace/ledger/adapter machinery cannot reach them, and
        # the replica entrypoint exposes the core engine knobs only
        if (args.url is not None or args.router or args.replicas > 1
                or args.fault_rate > 0 or args.speculative == "on"
                or args.adapters or args.tp > 1 or args.trace_out
                or args.profile
                or sum([args.spec_ab, args.trace_ab, args.kv_ab,
                        args.lora_ab, args.tp_ab, args.slo_ab,
                        args.profile_ab, args.overload_ab])):
            print("--fleet is its own A/B over subprocess replicas; "
                  "it composes with the load/engine-size/SLO knobs "
                  "only (no --url/--router/--replicas/--fault-rate/"
                  "--speculative/--adapters/--tp/--trace-out/"
                  "--profile/other --*-ab)", file=sys.stderr)
            return 2
    args.router = args.router or args.replicas > 1
    if args.router and (args.url is not None or args.fault_rate > 0
                        or args.spec_ab or args.speculative == "on"):
        print("--replicas/--router is in-process and drives its own "
              "chaos (--kill-replica-at); it composes with neither "
              "--url nor --fault-rate/--spec-ab/--speculative",
              file=sys.stderr)
        return 2
    if (args.kill_replica_at is not None and not args.router
            and not args.fleet):
        print("--kill-replica-at needs --router/--replicas > 1 "
              "or --fleet", file=sys.stderr)
        return 2
    if (args.adapters or args.lora_ab) and (args.url is not None
                                            or args.router):
        print("--adapters/--lora-ab need the single in-process engine "
              "(no --url, no --router/--replicas)", file=sys.stderr)
        return 2
    if args.adapters < 0:
        print("--adapters must be >= 0", file=sys.stderr)
        return 2

    # open loop: the full arrival schedule AND every prompt are drawn
    # BEFORE any server exists, so the --spec-ab arms replay IDENTICAL
    # load
    arrivals, t = [], 0.0
    for _ in range(args.requests):
        t += rng.expovariate(args.rate)
        arrivals.append(t)
    vocab = _TOY_VOCAB     # asserted against the model in _run_arm
    # the shared system prompt is drawn ONCE (seeded) so every request
    # carries an identical N-token head — the prefix-cache A/B's load
    # shape; the per-request tail keeps the configured distribution
    shared_prefix = [rng.randrange(vocab)
                     for _ in range(args.shared_prefix_len)]

    def _body(n):
        # --repeat-unit: self-repetitive prompt bodies (the n-gram
        # proposer's accepting case); each prompt tiles its OWN seeded
        # unit so prompts stay distinct across requests
        if args.repeat_unit > 0 and n > 0:
            u = [rng.randrange(vocab)
                 for _ in range(min(args.repeat_unit, n))]
            return (u * (n // len(u) + 1))[:n]
        return [rng.randrange(vocab) for _ in range(n)]

    prompts = [shared_prefix
               + _body(_draw_len(rng, args.prompt_dist, lo, hi))
               for _ in range(args.requests)]
    if args.wire_chaos:
        return _wire_chaos(args, prompts)
    # the per-request ADAPTER assignment is drawn up front too: the
    # --lora-ab arms replay the identical mix (the base arm just
    # ignores it), and the mix entropy record describes the LOAD, not
    # one arm's sampling
    n_adapters = args.adapters
    if args.lora_ab and n_adapters == 0:
        n_adapters = 8          # the PERF.md reference A/B: K=0 vs K=8
    if n_adapters:
        wts = ([1.0 / (j + 1) ** 1.1 for j in range(n_adapters)]
               if args.adapter_dist == "zipf" else None)
        assign = rng.choices([f"ad{j}" for j in range(n_adapters)],
                             weights=wts, k=args.requests)
    else:
        assign = [None] * args.requests
    # the per-request TENANT assignment for --overload-ab is drawn up
    # front too: all three arms replay the identical 60%-hot mix (one
    # hot tenant, four 10% cold ones), so the cold-goodput verdict
    # compares the SAME cold requests across arms
    tenants = [None] * args.requests
    if args.overload_ab:
        tenants = rng.choices(["hot", "c0", "c1", "c2", "c3"],
                              weights=[0.6, 0.1, 0.1, 0.1, 0.1],
                              k=args.requests)

    spec_def = args.speculative == "on"
    trace_def = args.trace_out is not None
    if args.spec_ab:
        # three arms on the identical pre-drawn load: "spec" is pinned
        # to host-mode drafting (the arm name existing baselines key
        # on), "specdev" runs the fused device-resident program
        arms = [("plain", False, trace_def), ("spec", True, trace_def),
                ("specdev", True, trace_def)]
    elif args.trace_ab:
        arms = [("traceoff", spec_def, False),
                ("traceon", spec_def, True)]
    elif args.kv_ab:
        arms = [("bf16", spec_def, trace_def),
                ("int8", spec_def, trace_def)]
    elif args.lora_ab:
        arms = [("base", spec_def, trace_def),
                ("lora", spec_def, trace_def)]
    elif args.slo_ab:
        arms = [("slooff", spec_def, trace_def),
                ("sloon", spec_def, trace_def)]
    elif args.profile_ab:
        arms = [("ledgeroff", spec_def, trace_def),
                ("ledgeron", spec_def, trace_def)]
    elif args.overload_ab:
        arms = [("cap", spec_def, trace_def),
                ("ctrloff", spec_def, trace_def),
                ("ctrlon", spec_def, trace_def)]
    elif args.tp_ab:
        tp_n = args.tp if args.tp > 1 else 2
        arms = [("tp1", spec_def, trace_def),
                (f"tp{tp_n}", spec_def, trace_def)]
    elif args.fleet:
        arms = [("mono", spec_def, trace_def),
                ("fleet", spec_def, trace_def)]
    else:
        arms = [("", spec_def, trace_def)]
    res = {}
    for arm, spec_on, trace_on in arms:
        arm_args = args
        if args.spec_ab:
            arm_args = argparse.Namespace(**vars(args))
            arm_args.spec_mode = ("device" if arm == "specdev"
                                  else "host")
        if args.kv_ab:
            # EQUAL HBM across the arms: int8 pages cost half the
            # bytes, so the int8 pool gets twice the pages — the
            # capacity half of the quantization win, visible as
            # halved serve_kv_occupancy at matched load
            arm_args = argparse.Namespace(**vars(args))
            arm_args.kv_dtype = arm
            if arm == "int8":
                arm_args.num_pages = 2 * args.num_pages
        if args.lora_ab:
            arm_args = argparse.Namespace(**vars(args))
            arm_args.adapters = 0 if arm == "base" else n_adapters
        if args.tp_ab:
            arm_args = argparse.Namespace(**vars(args))
            arm_args.tp = 1 if arm == "tp1" else tp_n
        if args.fleet:
            # EQUAL SILICON across the arms: the fleet arm holds N
            # engines of the base size in N processes; the monolithic
            # baseline gets the same total pool/batch/queue in ONE —
            # the per-chip memory wall is exactly what it does NOT
            # model, which is the fleet's whole reason to exist
            arm_args = argparse.Namespace(**vars(args))
            if arm == "mono":
                arm_args.fleet = 0
                arm_args.num_pages = args.num_pages * args.fleet
                arm_args.max_batch = args.max_batch * args.fleet
                arm_args.max_queue = args.max_queue * args.fleet
            else:
                arm_args.router = True   # fleet accounting in _run_arm
        if args.profile_ab:
            # the OFF arm is the disabled path the one-bool-branch
            # discipline promises is free; the ON arm pays the
            # signature-lookup + digest-observe cost being measured
            arm_args = argparse.Namespace(**vars(args))
            arm_args.profile = arm == "ledgeron"
        mon_on = True
        if args.slo_ab and arm == "slooff":
            # the OFF arm is the disabled path the PR 1/8 bar promises
            # is near-zero: FLAGS_enable_monitor off, no policy — the
            # serving seams pay one bool branch each
            arm_args = argparse.Namespace(**vars(args))
            arm_args.slo_ttft = arm_args.slo_tpot = None
            mon_on = False
        arm_arrivals = arrivals
        if args.overload_ab:
            # ctrloff/ctrlon replay the cap arm's schedule compressed
            # by --overload-factor: the IDENTICAL requests arrive at
            # 2x the at-capacity rate — the only knob that differs
            # between the overload arms is the control plane itself
            arm_args = argparse.Namespace(**vars(args))
            arm_args.control_on = arm == "ctrlon"
            if arm != "cap":
                arm_arrivals = [t / args.overload_factor
                                for t in arrivals]
        res[arm] = _run_arm(arm_args, arm, spec_on, trace_on, prompts,
                            arm_arrivals, assign, mon_on=mon_on,
                            tenants=tenants)
    if args.trace_ab:
        # the overhead verdict: decode cadence with the recorder on vs
        # off, on identical replayed load — the number that justifies
        # leaving tracing available in production serving
        a, b = res["traceoff"], res["traceon"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_trace_tpot_overhead",
                              "value": round(b["tpot_p50"]
                                             / a["tpot_p50"], 3),
                              "unit": "x (on/off)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_trace_throughput_ratio",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (on/off)"}))
    if args.profile_ab:
        # the overhead verdict: decode cadence with the program ledger
        # on vs off, on identical replayed load — per dispatch the on
        # path pays one arg-signature tuple + dict hit + digest
        # observe; the bar is <= 1.05x (ISSUE 16 acceptance)
        a, b = res["ledgeroff"], res["ledgeron"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_profile_tpot_overhead",
                              "value": round(b["tpot_p50"]
                                             / a["tpot_p50"], 3),
                              "unit": "x (on/off)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_profile_throughput_ratio",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (on/off)"}))
    if args.slo_ab:
        # the overhead verdict: decode cadence with the monitor + SLO
        # recording path on vs fully off, on identical replayed load —
        # the number that justifies leaving SLO scoring on in
        # production serving (PR 8 precedent: <= 1.02x is the bar)
        a, b = res["slooff"], res["sloon"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_slo_tpot_overhead",
                              "value": round(b["tpot_p50"]
                                             / a["tpot_p50"], 3),
                              "unit": "x (on/off)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_slo_throughput_ratio",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (on/off)"}))
    if args.overload_ab:
        # the overload verdict (ISSUE 19 acceptance): under identical
        # 2x-capacity load, the control plane sheds the HOT tenant at
        # the door and the COLD tenants keep (>= 90% of) the
        # at-capacity goodput they had before the overload; without
        # it, the queue backs up and goodput collapses for everyone.
        # This prices the MECHANISM (admission-door discrimination),
        # not a speedup — no arm decodes any faster than another.
        cap, off, on = res["cap"], res["ctrloff"], res["ctrlon"]
        for name, a in (("ctrloff", off), ("ctrlon", on)):
            if cap.get("cold_goodput") and a.get("cold_goodput") \
                    is not None:
                print(json.dumps(
                    {"metric": f"serve_overload_cold_retention_{name}",
                     "value": round(a["cold_goodput"]
                                    / cap["cold_goodput"], 4),
                     "unit": f"x ({name}/cap cold goodput)"}))
        print(json.dumps({"metric": "serve_overload_factor",
                          "value": args.overload_factor,
                          "unit": "x capacity"}))
        if cap.get("cold_goodput") and on.get("cold_goodput") \
                is not None and off.get("cold_goodput") is not None:
            ret_on = on["cold_goodput"] / cap["cold_goodput"]
            ret_off = off["cold_goodput"] / cap["cold_goodput"]
            verdict = ("PASS" if ret_on >= 0.9 and on.get("sheds", 0)
                       else "FAIL")
            print(f"overload verdict: {verdict} — ctrlon cold-tenant "
                  f"goodput retention {ret_on:.3f} (bar >= 0.9, "
                  f"{on.get('sheds', 0)} hot sheds) vs ctrloff "
                  f"{ret_off:.3f}")
    if args.spec_ab:
        # the A/B verdict: decode cadence and throughput, spec over
        # plain, on the identical replayed load
        a, b = res["plain"], res["spec"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_spec_tpot_p50_speedup",
                              "value": round(a["tpot_p50"]
                                             / b["tpot_p50"], 3),
                              "unit": "x (plain/spec)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_spec_throughput_speedup",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (spec/plain)"}))
        # the host-vs-device verdict: same drafts, same acceptance —
        # the ratio isolates what the per-step proposer round-trip
        # costs (on CPU-tiny it prices the MECHANISM; on-chip the
        # eliminated syncs are the latency frontier — see PERF.md)
        d = res["specdev"]
        if b.get("tpot_p50") and d.get("tpot_p50"):
            print(json.dumps({"metric": "serve_spec_mode_tpot_speedup",
                              "value": round(b["tpot_p50"]
                                             / d["tpot_p50"], 3),
                              "unit": "x (host/device)"}))
    if args.lora_ab:
        # the multi-tenant verdict: decode cadence with the
        # batched-adapter gather in the program vs without, on the
        # identical replayed load — the per-token price of serving K
        # fine-tunes from one engine
        a, b = res["base"], res["lora"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_lora_tpot_overhead",
                              "value": round(b["tpot_p50"]
                                             / a["tpot_p50"], 3),
                              "unit": "x (lora/base)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_lora_throughput_ratio",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (lora/base)"}))
    if args.tp_ab:
        # the tensor-parallel verdict on identical replayed load:
        # decode cadence TP=1/TP=N (on CPU meshes this measures the
        # MECHANISM + partition overhead — psums are free-ish on ICI,
        # not on a host mesh), and the capacity headline: the weights+
        # pool bytes a TP=N engine holds are spread over N chips, so
        # at FIXED per-chip HBM the servable model is N x what one
        # chip loads — the record a 13B/65B memory-fit config cashes
        a, b = res["tp1"], res[f"tp{tp_n}"]
        print(json.dumps({"metric": "serve_tp_degree",
                          "value": tp_n, "unit": "devices"}))
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_tp_tpot_speedup",
                              "value": round(a["tpot_p50"]
                                             / b["tpot_p50"], 3),
                              "unit": "x (tp1/tpN)"}))
        if a.get("model_bytes"):
            # per-chip footprint of the TP=1 arm x N: the largest
            # (weights + KV pool) total a TP=N mesh can serve at the
            # unsharded arm's per-chip HBM budget
            print(json.dumps({"metric": "serve_tp_max_model_bytes",
                              "value": a["model_bytes"] * tp_n,
                              "unit": "bytes (at TP=1 per-chip HBM)"}))
        if b.get("model_bytes"):
            print(json.dumps(
                {"metric": "serve_tp_bytes_per_chip",
                 "value": b["model_bytes"] // tp_n,
                 "unit": "bytes/chip (weights+pool, TP arm)"}))
    if args.fleet:
        # the cross-process verdict on identical replayed load: what
        # the HTTP hop + router fan-out cost against ONE process
        # holding the same total silicon. TTFT carries the per-request
        # connection + admission-probe price; TPOT should track the
        # mono arm closely (streaming rides one long-lived response);
        # throughput says whether N schedulers beat one big batch at
        # this arrival rate. Equal-silicon is the FAIR baseline and
        # also the fleet's ceiling — its floor (the mono arm cannot
        # model it) is the per-chip memory wall that forces the fleet
        # shape in the first place
        a, b = res["mono"], res["fleet"]
        print(json.dumps({"metric": "serve_fleet_replicas",
                          "value": args.fleet, "unit": "processes"}))
        if a.get("ttft_p50") and b.get("ttft_p50"):
            print(json.dumps({"metric": "serve_fleet_ttft_overhead",
                              "value": round(b["ttft_p50"]
                                             / a["ttft_p50"], 3),
                              "unit": "x (fleet/mono)"}))
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_fleet_tpot_overhead",
                              "value": round(b["tpot_p50"]
                                             / a["tpot_p50"], 3),
                              "unit": "x (fleet/mono)"}))
        if a.get("throughput") and b.get("throughput"):
            print(json.dumps(
                {"metric": "serve_fleet_throughput_ratio",
                 "value": round(b["throughput"] / a["throughput"], 3),
                 "unit": "x (fleet/mono)"}))
    if args.kv_ab:
        # the quantization verdict on identical replayed load: decode
        # cadence bf16/int8 (HBM-bound hardware converts the halved
        # read bytes into TPOT; CPU-tiny measures the MECHANISM),
        # effective page capacity at equal HBM from the REAL per-page
        # byte costs (scale overhead included), and the bounded-
        # numerics probe — max next-token logit divergence + greedy
        # token flips on a fresh engine pair
        a, b = res["bf16"], res["int8"]
        if a.get("tpot_p50") and b.get("tpot_p50"):
            print(json.dumps({"metric": "serve_kv_quant_tpot_speedup",
                              "value": round(a["tpot_p50"]
                                             / b["tpot_p50"], 3),
                              "unit": "x (bf16/int8)"}))
        if b.get("kv_page_cost"):
            # effective page capacity at equal HBM vs the bf16
            # PRODUCTION baseline (the toy model's f32 cache dtype
            # must not inflate this): bf16-equivalent bytes over the
            # int8 arm's actual per-page cost, scale overhead included
            cost = b["kv_page_cost"]
            print(json.dumps(
                {"metric": "serve_kv_quant_capacity_ratio",
                 "value": round(cost["bf16_equiv_bytes_per_page"]
                                / cost["bytes_per_page"], 3),
                 "unit": "x pages at equal HBM (vs bf16)"}))
        div = _kv_quant_divergence(args, prompts)
        print(f"kv quant numerics: max logit div "
              f"{div['max_logit_div']:.4f} (mean "
              f"{div['mean_logit_div']:.4f}), {div['token_flips']} "
              f"greedy token flips over {div['tokens']} tokens")
        print(json.dumps({"metric": "serve_kv_quant_max_logit_div",
                          "value": round(div["max_logit_div"], 6),
                          "unit": "logit"}))
        print(json.dumps({"metric": "serve_kv_quant_token_flips",
                          "value": div["token_flips"],
                          "unit": "count"}))
    return 0


def _wire_chaos(args, prompts) -> int:
    """--wire-chaos: two arms over the REAL wire. Each arm builds a
    fresh seeded prefill/decode server pair behind ``serve_http`` and
    drives the identical pre-drawn load through a ``RemoteReplica``;
    the chaos arm replays it through an injected
    delay/drop/half-close/corrupt ``NetworkFaultPlan`` at both seams
    (generate + kv_import). The driver replays a request once on a
    terminal wire failure (the failover the router would run), so the
    verdict is exactly-once SURVIVAL: every request finishes and its
    tokens are bitwise-identical to the clean arm's — injected chaos
    shows up in the resume/retry/reship counters, never the output."""
    import argparse as _ap

    import numpy as np

    from paddle_tpu import tracing
    from paddle_tpu.inference.generation import GenerationConfig
    from paddle_tpu.serving import (DisaggregatedFront, RemoteReplica,
                                    RequestFailed, RequestRejected)
    from paddle_tpu.serving.http import serve_http
    from paddle_tpu.testing.faults import NetworkFaultPlan

    # the ship phase needs the paged prefix cache on both sides
    if args.cache_prefixes != "on":
        args = _ap.Namespace(**vars(args))
        args.cache_prefixes = "on"
    cfg = GenerationConfig(max_new_tokens=args.max_new,
                           do_sample=False)
    # the requests a ship cycle exports: longest prompts first — at
    # least one FULL page-size block resident from their prefill
    ship = sorted(range(len(prompts)),
                  key=lambda i: -len(prompts[i]))[:4]

    def _run(chaos: bool) -> dict:
        arm = "wirechaos" if chaos else "wireclean"
        tracing.clear()
        if chaos:
            tracing.enable()
        srv1 = srv2 = httpd1 = httpd2 = rep = rep2 = None
        try:
            srv1, vocab, _ = _build_toy_server(args, False)
            srv2, _, _ = _build_toy_server(args, False)
            assert vocab >= _TOY_VOCAB
            httpd1, httpd2 = serve_http(srv1), serve_http(srv2)
            rep = RemoteReplica(
                f"http://127.0.0.1:{httpd1.server_address[1]}")
            rep2 = RemoteReplica(
                f"http://127.0.0.1:{httpd2.server_address[1]}")
            assert rep.wait_ready(timeout=120)
            assert rep2.wait_ready(timeout=120)
            plan = None
            if chaos:
                plan = NetworkFaultPlan()
                # generate seam: one of each injection, spread over
                # the (sequential, so deterministic) call sequence.
                # Retries/resumes count as calls too — the plan fires
                # strictly by call order, same as a real flaky link.
                plan.delay_at("generate", nth=2, seconds=0.05)
                plan.drop_at("generate", nth=4)       # submit retry
                plan.half_close_at("generate", nth=6, after=1)
                plan.corrupt_at("generate", nth=9, mode="flip",
                                after=1)              # garbled line
                # three consecutive tears exhaust the resume budget
                # (default 2) and force the failover replay
                plan.half_close_at("generate", nth=11, after=1,
                                   times=3)
                # kv_import seam: both corruption modes + a delay
                plan.corrupt_at("kv_import", nth=1, mode="flip")
                plan.delay_at("kv_import", nth=2, seconds=0.02)
                plan.corrupt_at("kv_import", nth=3, mode="truncate")
                rep.fault_plan = plan
                rep2.fault_plan = plan
            tokens, failovers, failures = [], 0, 0
            for p in prompts:
                ids = np.asarray(p, np.int32)
                toks = None
                for attempt in (0, 1):
                    try:
                        h = rep.submit(ids, cfg)
                        toks = [int(t)
                                for t in h.result(timeout=120)]
                        break
                    except (RequestFailed, RequestRejected,
                            RuntimeError, TimeoutError):
                        if attempt:
                            failures += 1
                        else:
                            failovers += 1   # the replay the router
                            #                  would run elsewhere
                tokens.append(toks)
            # ship phase: prefill pages for the longest prompts are
            # resident on srv1 (their requests just ran there) — ship
            # them to the decode server through the faulted seam
            front = DisaggregatedFront(rep, rep2)
            ship_fail = 0
            for i in ship:
                try:
                    front.ship(prompts[i])
                except Exception:
                    ship_fail += 1
            out = {
                "tokens": tokens, "failovers": failovers,
                "failures": failures, "ship_failures": ship_fail,
                "resumes": rep.resumes,
                "submit_retries": rep.submit_retries,
                "reships": front.reships,
                "integrity_rejects": rep2.integrity_rejects,
                "injected": list(plan.injected) if plan else [],
            }
            if chaos and args.trace_out:
                tracing.export_chrome(args.trace_out)
                print(f"wrote wire trace to {args.trace_out} "
                      f"(tools/monitor_report.py --wire "
                      f"{args.trace_out})")
            return out
        finally:
            for r in (rep, rep2):
                if r is not None:
                    r.close()
            for hd in (httpd1, httpd2):
                if hd is not None:
                    hd.shutdown()
            for s in (srv1, srv2):
                if s is not None:
                    s.shutdown(drain=False)
            tracing.disable()
            tracing.clear()

    res = {"wireclean": _run(False), "wirechaos": _run(True)}
    a, b = res["wireclean"], res["wirechaos"]
    matched = sum(1 for x, y in zip(a["tokens"], b["tokens"])
                  if x is not None and x == y)
    survival = matched / max(1, len(prompts))
    for arm in ("wireclean", "wirechaos"):
        r = res[arm]
        print(f"{arm}: {sum(1 for t in r['tokens'] if t is not None)}"
              f"/{len(prompts)} finished, {r['resumes']} resumes, "
              f"{r['submit_retries']} submit retries, "
              f"{r['failovers']} failovers, {r['reships']} reships, "
              f"{r['integrity_rejects']} integrity rejects, "
              f"{len(r['injected'])} injections")
    for name, val in (("serve_wire_resumes", b["resumes"]),
                      ("serve_wire_failovers", b["failovers"]),
                      ("serve_wire_reships", b["reships"]),
                      ("serve_wire_integrity_rejects",
                       b["integrity_rejects"]),
                      ("serve_wire_submit_retries",
                       b["submit_retries"])):
        print(json.dumps({"metric": name, "value": int(val),
                          "unit": "count"}))
    print(json.dumps({"metric": "serve_wire_survival_rate",
                      "value": round(survival, 4),
                      "unit": "fraction (chaos tokens == clean)"}))
    ok = (survival == 1.0 and b["ship_failures"] == 0
          and len(b["injected"]) > 0
          and (b["resumes"] or b["submit_retries"])
          and b["integrity_rejects"])
    print(f"wire verdict: {'PASS' if ok else 'FAIL'} — survival "
          f"{survival:.3f} (bar 1.0) under {len(b['injected'])} "
          f"injections; {b['resumes']} mid-stream resumes, "
          f"{b['submit_retries']} idempotent submit retries, "
          f"{b['integrity_rejects']} corrupt ships rejected "
          f"({b['reships']} re-shipped clean)")
    return 0 if ok else 1


def _kv_quant_divergence(args, prompts, n_prompts: int = 3,
                         steps: int = 16):
    """Bounded-numerics probe for the --kv-ab verdict: one fresh
    bf16/int8 engine pair (identical seeded weights), the run's first
    few prompts, stepwise next-token logit comparison through the REAL
    store/read pipeline (quantization.kv.max_logit_divergence)."""
    import argparse as _ap

    from paddle_tpu.quantization.kv import max_logit_divergence

    pa = _ap.Namespace(**vars(args))
    pa.kv_dtype = "bf16"
    pb = _ap.Namespace(**vars(args))
    pb.kv_dtype = "int8"
    eng_a, _ = _toy_engine(pa)
    eng_b, _ = _toy_engine(pb)
    import numpy as np

    # prompt + probe steps must fit one sequence's max_len; with a
    # tiny --max-pages the step count shrinks rather than the cap
    # going negative and silently mis-slicing (or emptying) prompts
    max_len = args.max_pages * args.page_size
    steps = max(1, min(steps, max_len // 2))
    cap = max(1, max_len - steps - 1)
    use = [np.asarray(p[:cap], np.int32)
           for p in prompts[:n_prompts]]
    try:
        return max_logit_divergence(eng_a, eng_b, use, steps=steps)
    finally:
        eng_a.close()
        eng_b.close()


def _ttft_decomposition():
    """Split each finished request's TTFT into its trace-derived phase
    shares: queue wait (enqueue -> dequeue), admission prefill (the
    admit/chunk span durations), and the remainder — scheduler gap +
    the first decode segment's share. Returns (queue, prefill, gap)
    second-lists over the requests whose enqueue AND first token are
    still in the bounded ring."""
    from paddle_tpu import tracing

    per = {}
    for e in tracing.events():
        rid, ph = e.get("rid"), e["phase"]
        if rid is None:
            continue
        d = per.setdefault(rid, {})
        if ph == "queue.enqueue":
            d["enq"] = e["ts_ns"]
        elif ph == "queue.dequeue" and "deq" not in d:
            d["deq"] = e["ts_ns"]
        elif ph in ("admit", "admit.begin", "prefill_chunk"):
            # only spans BEFORE the first token count toward TTFT: a
            # preempted request's replay re-admission happens after it
            # and must not inflate the prefill share (ring insertion
            # order is end-time order, so the gate below is exact —
            # the first admission's span lands before first_token)
            if "first" not in d:
                d["admit"] = d.get("admit", 0) + e["dur_ns"]
        elif ph == "first_token" and "first" not in d:
            d["first"] = e["ts_ns"]
    qs, ps, gs = [], [], []
    for d in per.values():
        if "enq" not in d or "first" not in d:
            continue
        ttft = (d["first"] - d["enq"]) / 1e9
        q = max((d.get("deq", d["enq"]) - d["enq"]) / 1e9, 0.0)
        p = d.get("admit", 0) / 1e9
        qs.append(q)
        ps.append(p)
        gs.append(max(ttft - q - p, 0.0))
    return qs, ps, gs


def _load_bench_adapters(server, args) -> None:
    """Hot-load ``--adapters`` seeded synthetic LoRA adapters through
    the Server's admin path (the same inter-segment-gap marshalling a
    production load uses). Factors are small (0.05 std) so the toy
    model's outputs stay well-formed while the gather does real
    work."""
    import numpy as np

    reg = server.engine.adapters
    for j in range(args.adapters):
        g = np.random.default_rng(1000 + j)
        params = {
            t: (g.standard_normal((args.lora_rank, d_in))
                .astype(np.float32) * 0.05,
                g.standard_normal((d_out, args.lora_rank))
                .astype(np.float32) * 0.05)
            for t, (d_in, d_out) in reg.shapes.items()}
        server.load_adapter(f"ad{j}", params)


def _run_arm(args, arm: str, spec_on: bool, trace_on: bool, prompts,
             arrivals, assign=None, mon_on: bool = True,
             tenants=None) -> dict:
    """Build one server (in-process mode), drive the pre-drawn load
    through it, print the table + BENCH records (metric names suffixed
    ``_<arm>`` in A/B mode), shut down. ``assign`` is the pre-drawn
    per-request adapter name list (ignored when --adapters is 0 for
    this arm); ``tenants`` the pre-drawn per-request tenant list
    (--overload-ab — the hot/cold mix every arm replays).
    ``mon_on=False`` (the --slo-ab OFF arm) runs with
    FLAGS_enable_monitor disabled — the one-bool-branch path.
    Returns the numbers the A/B verdict needs."""
    sfx = f"_{arm}" if arm else ""
    if assign is None:
        assign = [None] * len(prompts)
    if tenants is None:
        tenants = [None] * len(prompts)
    server = None
    plan = None
    kill_fn = None
    if args.url is None:
        from paddle_tpu import monitor, tracing
        from paddle_tpu.monitor import ledger
        if mon_on:
            monitor.enable()
        else:
            monitor.disable()
        monitor.reset()    # per-arm program/compile counters
        ledger.reset()     # per-arm program records
        if getattr(args, "profile", False):
            ledger.enable()
        else:
            ledger.disable()
        tracing.clear()    # per-arm ring (the off arm must not export
        #                    the on arm's leftovers)
        if trace_on:
            tracing.enable()
        else:
            tracing.disable()
        if getattr(args, "fleet", 0):
            server, vocab, kill_fn = _build_fleet_router(args)
        elif args.router:
            server, vocab, kill_fn = _build_toy_router(args)
        else:
            server, vocab, plan = _build_toy_server(args, spec_on)
            if args.adapters:
                _load_bench_adapters(server, args)
        # prompts were drawn in [0, _TOY_VOCAB) before the server
        # existed; any preset with at least that many tokens serves
        # them (tiny == exactly; 13b/65b have 32000)
        assert vocab >= _TOY_VOCAB, \
            f"model vocab {vocab} < {_TOY_VOCAB} the prompts used"

    stats = _Stats()
    # KV pool occupancy sampler (in-process paged engine): the
    # utilization half of the reserved-vs-optimistic A/B — reserved
    # mode's occupancy counts RESERVED pages (worst case held against
    # the pool), optimistic mode's counts pages actually written
    occ_samples = []
    occ_stop = threading.Event()
    occ_th = None
    eng = getattr(server, "engine", None)   # a Router has replicas,
    #                                         not one engine
    alloc = getattr(eng, "alloc", None) if eng is not None else None
    # HBM cost per page under this arm's storage dtype (scales
    # included) + its bf16-equivalent baseline — the --kv-ab
    # capacity-ratio record divides these
    bpp_fn = getattr(eng, "kv_page_cost", None)
    kv_page_cost = bpp_fn() if callable(bpp_fn) else None
    # weights + KV pool bytes this engine holds on device (logical
    # totals; a TP mesh spreads them over tp_degree chips) — the
    # --tp-ab capacity record's numerator
    model_bytes = None
    if eng is not None and getattr(eng, "params", None) is not None:
        model_bytes = sum(int(v.nbytes) for v in eng.params.values())
        if kv_page_cost is not None:
            model_bytes += (kv_page_cost["bytes_per_page"]
                            * eng.num_pages)
    if alloc is not None:
        def _sample_occ():
            while not occ_stop.wait(0.005):
                occ_samples.append(alloc.occupancy)

        occ_th = threading.Thread(target=_sample_occ, daemon=True)
        occ_th.start()
    threads = []
    kill_timer = None
    t_start = time.monotonic()
    if kill_fn is not None:
        kill_timer = threading.Timer(args.kill_replica_at, kill_fn)
        kill_timer.daemon = True
        kill_timer.start()
    for i, (at, prompt) in enumerate(zip(arrivals, prompts)):
        delay = t_start + at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        if args.url is None:
            from paddle_tpu.inference.generation import GenerationConfig
            import numpy as np

            cfg = GenerationConfig(
                max_new_tokens=args.max_new,
                adapter=(assign[i] if args.adapters else None))
            th = threading.Thread(
                target=_drive_inproc,
                args=(server, np.asarray(prompt, np.int32), cfg, stats,
                      tenants[i]))
        else:
            th = threading.Thread(
                target=_drive_http,
                args=(args.url, prompt,
                      {"max_new_tokens": args.max_new}, stats))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    wall = time.monotonic() - t_start
    if kill_timer is not None:
        # a run that drained before T must not (a) leave the timer to
        # fire into a later A/B arm, or (b) silently report a
        # NO-FAULT run as the fault-plan arm
        kill_timer.cancel()
        if not kill_fn.fired["kill"]:
            print(f"warning: --kill-replica-at {args.kill_replica_at} "
                  "never fired (the run finished first) — the fleet "
                  "records below reflect an UNFAULTED run; lower the "
                  "kill time or raise --requests", file=sys.stderr)
    if occ_th is not None:
        occ_stop.set()
        occ_th.join(timeout=2.0)

    done = len(stats.e2e)
    print(f"\n[{arm or 'run'}] {done}/{args.requests} completed, "
          f"{stats.rejected} rejected, {stats.failed} failed, "
          f"{stats.tokens} tokens in {wall:.2f}s "
          f"({stats.tokens / wall:.1f} tok/s)\n")
    # provenance header: ties this arm's records to the machine/
    # backend/rev that produced them — tools/bench_diff.py reads it
    # and warns when two compared rounds disagree
    from paddle_tpu.monitor.provenance import env_stamp
    print(json.dumps({"metric": "bench_env",
                      **env_stamp(extra={"tp_degree": args.tp,
                                         "arm": arm or "run"})}))
    rows = [("ttft", stats.ttft, "s"), ("tpot", stats.tpot, "s"),
            ("e2e_latency", stats.e2e, "s")]
    print(f"{'METRIC':<14}{'p50':>10}{'p90':>10}{'p99':>10}")
    for name, xs, _u in rows:
        print(f"{name:<14}"
              f"{_percentile(xs, 50):>10.4f}"
              f"{_percentile(xs, 90):>10.4f}"
              f"{_percentile(xs, 99):>10.4f}")
    print()
    for name, xs, unit in rows:
        if not xs:
            continue   # NaN is not valid JSON; the table above shows it
        for q in (50, 90, 99):
            print(json.dumps({"metric": f"serve_{name}_p{q}{sfx}",
                              "value": round(_percentile(xs, q), 6),
                              "unit": unit}))
    print(json.dumps({"metric": f"serve_throughput{sfx}",
                      "value": round(stats.tokens / wall, 2),
                      "unit": "tokens/s"}))
    print(json.dumps({"metric": f"serve_rejected{sfx}",
                      "value": stats.rejected, "unit": "count"}))
    if server is not None and mon_on and not getattr(args, "fleet", 0):
        # the bucketing win in the methodology: how many prefill
        # programs this run compiled (and what that cost) — bounded by
        # len(buckets)+1 with bucketing on, O(#distinct lengths) off
        # (--fleet arm: the compiles happened in the CHILD processes;
        # the local registry would report a misleading zero)
        pre_n, pre_s, all_n, all_s = _prefill_program_stats()
        n_lens = len({len(p) for p in prompts})
        print(f"prefill programs compiled: {pre_n} "
              f"({pre_s:.2f}s) for {n_lens} distinct prompt lengths; "
              f"all jit programs: {all_n} ({all_s:.2f}s)")
        print(json.dumps({"metric": f"serve_prefill_programs{sfx}",
                          "value": pre_n, "unit": "count"}))
        print(json.dumps({"metric": f"serve_prefill_compile_seconds{sfx}",
                          "value": round(pre_s, 4), "unit": "s"}))
        print(json.dumps({"metric": f"serve_distinct_prompt_lens{sfx}",
                          "value": n_lens, "unit": "count"}))
    if alloc is not None:
        # memory-pressure accounting: the utilization/throughput A/B
        # (PERF.md) reads these four — occupancy tells how much of the
        # pool the policy actually used, preemptions + the latency
        # penalty tell what the optimistic win cost in tail latency
        occ50, occ99 = (_percentile(occ_samples, 50),
                        _percentile(occ_samples, 99))
        pre = alloc.preemptions
        n_pre = len(stats.e2e_preempted)
        print(f"kv pool [{args.admission_mode}]: occupancy "
              f"p50={occ50:.3f} p99={occ99:.3f}, {pre} preemptions, "
              f"{n_pre} requests preempted >= once")
        if occ_samples:
            print(json.dumps({"metric": f"serve_kv_occupancy_p50{sfx}",
                              "value": round(occ50, 4),
                              "unit": "ratio"}))
            print(json.dumps({"metric": f"serve_kv_occupancy_p99{sfx}",
                              "value": round(occ99, 4),
                              "unit": "ratio"}))
        print(json.dumps({"metric": f"serve_kv_preemptions{sfx}",
                          "value": pre, "unit": "count"}))
        print(json.dumps({"metric": f"serve_preempted_requests{sfx}",
                          "value": n_pre, "unit": "count"}))
        n_clean = len(stats.e2e) - n_pre
        if n_pre and n_clean:
            penalty = (sum(stats.e2e_preempted) / n_pre
                       - (sum(stats.e2e) - sum(stats.e2e_preempted))
                       / n_clean)
            print(json.dumps(
                {"metric": f"serve_preempted_latency_penalty{sfx}",
                 "value": round(penalty, 6), "unit": "s"}))
        if plan is None:
            # chaos runs emit these below from fault accounting
            print(json.dumps({"metric": f"serve_requests_survived{sfx}",
                              "value": done, "unit": "count"}))
            print(json.dumps({"metric": f"serve_requests_failed{sfx}",
                              "value": stats.failed, "unit": "count"}))
        if getattr(alloc, "kv_dtype", "bf16") == "int8":
            # quantized-KV accounting: bytes the int8 layout avoided
            # for the pages this run claimed (scale overhead already
            # netted out) — the capacity half of the quantization win
            print(f"kv quant [int8]: {alloc.quant_bytes_saved} HBM "
                  f"bytes saved across claimed pages")
            print(json.dumps(
                {"metric": f"serve_kv_quant_bytes_saved{sfx}",
                 "value": alloc.quant_bytes_saved, "unit": "bytes"}))
        if args.shared_prefix_len > 0 or getattr(alloc, "prefix_cache",
                                                 False):
            # prefix-cache A/B: hit rate over lookups (cache off: both
            # zero — the cold column), prefill tokens whose compute a
            # warm admission skipped, shared-page high-water via the
            # pressure surface. Read alongside ttft_p50/p99 and
            # kv_occupancy above — the win is TTFT down AND occupancy
            # down at matched load
            hits = getattr(alloc, "prefix_hits", 0)
            looks = getattr(alloc, "prefix_lookups", 0)
            saved = getattr(alloc, "prefix_tokens_saved", 0)
            rate = hits / looks if looks else 0.0
            print(f"prefix cache [{args.cache_prefixes}]: "
                  f"{hits}/{looks} warm admissions "
                  f"(hit rate {rate:.3f}), {saved} prefill tokens "
                  f"saved, {getattr(alloc, 'cow_copies', 0)} CoW "
                  f"copies, {getattr(alloc, 'cached_pages', 0)} pages "
                  f"parked at exit")
            print(json.dumps({"metric": f"serve_prefix_hit_rate{sfx}",
                              "value": round(rate, 4),
                              "unit": "ratio"}))
            print(json.dumps({"metric": f"serve_prefill_tokens_saved{sfx}",
                              "value": saved, "unit": "tokens"}))
            print(json.dumps({"metric": f"serve_prefix_cow_copies{sfx}",
                              "value": getattr(alloc, "cow_copies", 0),
                              "unit": "count"}))
    reg = (getattr(eng, "adapters", None) if eng is not None
           else None)
    if reg is not None and args.adapters:
        # multi-tenant accounting: how many fine-tunes ONE engine
        # served this run, and how concentrated the mix was (entropy
        # over the drawn assignment — log2(K) = perfectly uniform,
        # lower = a few hot tenants; zipf loads land in between)
        import math
        from collections import Counter

        info = reg.resident()
        used = [a for a in assign if a is not None]
        cnt = Counter(used)
        n_u = len(used)
        ent = (-sum((c / n_u) * math.log2(c / n_u)
                    for c in cnt.values()) if n_u else 0.0)
        print(f"lora [{args.adapters} adapters, {args.adapter_dist}]: "
              f"{info['resident']} resident, {len(cnt)} distinct in "
              f"the mix, entropy {ent:.3f} bits "
              f"(max {math.log2(args.adapters):.3f})")
        print(json.dumps({"metric": f"serve_lora_adapters_resident{sfx}",
                          "value": info["resident"], "unit": "count"}))
        print(json.dumps({"metric": f"serve_lora_mix_entropy{sfx}",
                          "value": round(ent, 4), "unit": "bits"}))
    spec_stats = (getattr(eng, "spec_stats", None)
                  if eng is not None else None)
    if spec_stats is not None and getattr(eng, "draft_k", 0):
        # speculative-decoding accounting (spec arm / --speculative
        # on): accepted-tokens-per-forward is the number that converts
        # into TPOT on HBM-bound hardware; acceptance rate says how
        # well the n-gram proposer fit this load. CPU-tiny runs
        # measure the MECHANISM (the host proposer round-trip
        # dominates there), not the speedup — see PERF.md.
        ss = spec_stats()
        print(f"speculative [draft_k={args.draft_k}]: "
              f"{ss['emitted']} tokens / {ss['slot_steps']} slot-"
              f"forwards ({ss['forwards']} verify steps) = "
              f"{ss['tokens_per_forward']:.2f} tok/fwd per slot, "
              f"acceptance {ss['accepted']}/{ss['proposed']} "
              f"= {ss['acceptance_rate']:.3f}")
        print(json.dumps({"metric": f"serve_spec_tokens_per_forward{sfx}",
                          "value": round(ss["tokens_per_forward"], 4),
                          "unit": "tokens/forward"}))
        print(json.dumps({"metric": f"serve_spec_acceptance_rate{sfx}",
                          "value": round(ss["acceptance_rate"], 4),
                          "unit": "ratio"}))
        print(json.dumps({"metric": f"serve_spec_draft_tokens{sfx}",
                          "value": ss["proposed"], "unit": "tokens"}))
        # the sync-elimination receipt: host mode blocks on one
        # proposer readback per verify forward, device mode reads back
        # once per SEGMENT — this must print 0.0 there
        print(json.dumps(
            {"metric": f"serve_spec_host_syncs_per_token{sfx}",
             "value": round(ss["host_syncs_per_token"], 4),
             "unit": "syncs/token"}))
    if server is not None and args.router:
        # fleet accounting (PERF.md fleet-survival methodology): the
        # survival rate over ACCEPTED requests is the headline — with
        # spare replicas it should stay 1.0 through a replica kill;
        # failover count/latency price the migrations, breaker opens
        # count how often routing walled off a sick replica
        snap = server.load()
        accepted = args.requests - stats.rejected
        survival = done / accepted if accepted else 0.0
        per_rep = ", ".join(
            f"r{e['replica']}:{e['status']}"
            f"(breaker={e['breaker']['state']},"
            f"restarts={e['restarts']})" for e in snap["replicas"])
        print(f"fleet [{len(snap['replicas'])} replicas]: survival "
              f"{done}/{accepted} = {survival:.3f}, "
              f"{snap['failovers']} failovers, "
              f"{snap['breaker_opens']} breaker opens; {per_rep}")
        print(json.dumps({"metric": f"serve_fleet_survival_rate{sfx}",
                          "value": round(survival, 4),
                          "unit": "ratio"}))
        print(json.dumps({"metric": f"serve_failover_count{sfx}",
                          "value": snap["failovers"],
                          "unit": "count"}))
        if stats.e2e_failover:
            print(json.dumps(
                {"metric": f"serve_failover_latency_p99{sfx}",
                 "value": round(
                     _percentile(stats.e2e_failover, 99), 6),
                 "unit": "s"}))
        print(json.dumps({"metric": f"serve_breaker_opens{sfx}",
                          "value": snap["breaker_opens"],
                          "unit": "count"}))
        print(json.dumps({"metric": f"serve_replica_restarts{sfx}",
                          "value": sum(e["restarts"]
                                       for e in snap["replicas"]),
                          "unit": "count"}))
        print(json.dumps({"metric": f"serve_requests_survived{sfx}",
                          "value": done, "unit": "count"}))
        print(json.dumps({"metric": f"serve_requests_failed{sfx}",
                          "value": stats.failed, "unit": "count"}))
    if plan is not None:
        # chaos accounting: what was injected, what survived, what the
        # supervisor did about it (fault_stats is host-side — readable
        # even with the monitor off)
        fs = server.fault_stats()
        rec = sorted(fs["recovery_s"])
        print(f"chaos: {len(plan.injected)} faults injected "
              f"({args.fault_kind} @ {args.fault_site}), "
              f"{done} requests survived, {stats.failed} failed, "
              f"{fs['restarts']} engine restarts")
        print(json.dumps({"metric": f"serve_faults_injected{sfx}",
                          "value": len(plan.injected),
                          "unit": "count"}))
        print(json.dumps({"metric": f"serve_requests_survived{sfx}",
                          "value": done, "unit": "count"}))
        print(json.dumps({"metric": f"serve_requests_failed{sfx}",
                          "value": stats.failed, "unit": "count"}))
        print(json.dumps({"metric": f"serve_restarts{sfx}",
                          "value": fs["restarts"], "unit": "count"}))
        for q in (50, 90, 99):
            if rec:
                print(json.dumps(
                    {"metric": f"serve_recovery_p{q}{sfx}",
                     "value": round(_percentile(rec, q), 6),
                     "unit": "s"}))

    if (server is not None and mon_on
            and (args.slo_ttft is not None
                 or args.slo_tpot is not None)):
        # SLO/goodput accounting (PERF.md SLO methodology): the
        # GET /stats rollup — digest-exact percentiles (a Router's
        # version MERGES replica digests, never averages) scored
        # against the armed policy. serve_goodput is the headline:
        # the fraction of service-terminal requests the fleet served
        # INSIDE the SLO — the quantity disaggregation papers
        # optimize, where raw throughput can lie
        st = server.stats()
        tens = st.get("tenants") or {}
        met = sum(v.get("met", 0) for v in tens.values())
        missed = sum(v.get("missed", 0) for v in tens.values())
        parts = []
        for t, v in sorted(tens.items()):
            gp = v.get("goodput")
            parts.append(f"{t}:{'-' if gp is None else format(gp, '.3f')}"
                         f"(burn_f={v.get('burn_fast')})")
        print(f"slo [ttft<={args.slo_ttft} tpot<={args.slo_tpot}]: "
              f"goodput {met}/{met + missed}, per-tenant "
              + ", ".join(parts))
        if met + missed:
            print(json.dumps({"metric": f"serve_goodput{sfx}",
                              "value": round(met / (met + missed), 4),
                              "unit": "ratio"}))
        for metric, rec in (("ttft", "serve_slo_ttft_p99"),
                            ("tpot", "serve_slo_tpot_p99")):
            agg = (st.get("metrics") or {}).get(metric, {}).get("*")
            if agg and agg.get("p99") is not None:
                print(json.dumps({"metric": f"{rec}{sfx}",
                                  "value": agg["p99"], "unit": "s"}))
    extra = {}
    if server is not None and getattr(args, "overload_ab", False):
        # overload accounting (PERF.md overload methodology): the
        # verdict needs goodput SPLIT by tenant class — the control
        # plane's whole job is spending the hot tenant's availability
        # (shedding it at the door) to keep the cold tenants inside
        # SLO. Shed rejects + the control snapshot say what the plane
        # actually did; the cap arm prints zeros for both.
        st = server.stats()
        tens = st.get("tenants") or {}
        hm = hx = cm = cx = 0
        for t, v in tens.items():
            if t == "hot":
                hm += v.get("met", 0)
                hx += v.get("missed", 0)
            else:
                cm += v.get("met", 0)
                cx += v.get("missed", 0)
        cold_gp = cm / (cm + cx) if cm + cx else None
        hot_gp = hm / (hm + hx) if hm + hx else None
        ctrl = (server.load() or {}).get("control") or {}
        shed_total = sum(sum(r.values()) for r in
                         (ctrl.get("sheds") or {}).values())
        def fmt(g):
            return "-" if g is None else format(g, ".3f")

        print(f"overload [{arm}]: cold goodput {fmt(cold_gp)} "
              f"({cm}/{cm + cx}), hot goodput {fmt(hot_gp)} "
              f"({hm}/{hm + hx}), {stats.shed} shed rejects, "
              f"rung {ctrl.get('rung', 0)} "
              f"({ctrl.get('rung_action', 'off')}) at drain")
        if cold_gp is not None:
            print(json.dumps({"metric": f"serve_goodput_cold{sfx}",
                              "value": round(cold_gp, 4),
                              "unit": "ratio"}))
        if hot_gp is not None:
            print(json.dumps({"metric": f"serve_goodput_hot{sfx}",
                              "value": round(hot_gp, 4),
                              "unit": "ratio"}))
        print(json.dumps({"metric": f"serve_shed_rejects{sfx}",
                          "value": stats.shed, "unit": "count"}))
        met = sum(v.get("met", 0) for v in tens.values())
        missed = sum(v.get("missed", 0) for v in tens.values())
        extra = {"cold_goodput": cold_gp, "hot_goodput": hot_gp,
                 "goodput": (met / (met + missed) if met + missed
                             else None),
                 "sheds": shed_total}
    if server is not None and trace_on:
        # trace-derived TTFT decomposition: WHICH phase ate the time.
        # queue = submit->dequeue, prefill = the admission span(s),
        # gap = the remainder (scheduler gap + first segment share) —
        # the three sum to the server-side TTFT per request
        qs, ps, gs = _ttft_decomposition()
        if qs:
            print(f"ttft decomposition (n={len(qs)}): queue p50 "
                  f"{_percentile(qs, 50):.4f}s, prefill p50 "
                  f"{_percentile(ps, 50):.4f}s, gap p50 "
                  f"{_percentile(gs, 50):.4f}s")
            for name, xs in (("queue", qs), ("prefill", ps),
                             ("gap", gs)):
                print(json.dumps(
                    {"metric": f"serve_ttft_{name}_p50{sfx}",
                     "value": round(_percentile(xs, 50), 6),
                     "unit": "s"}))
        if args.trace_out:
            from paddle_tpu import tracing
            tpath = args.trace_out + sfx
            tracing.export_chrome(tpath)
            print(f"wrote trace to {tpath} (open in chrome://tracing "
                  f"or ui.perfetto.dev; tools/monitor_report.py "
                  f"--trace {tpath} for the phase table)")
    if server is not None and getattr(args, "profile", False):
        # program-ledger report: read BEFORE shutdown — engine.close()
        # retires the ledger rows the engine owns. The per-program
        # table is the "which compiled program is eating the step"
        # answer; the dispatch total cross-checks the monitored_jit
        # counters (ISSUE 16 acceptance: the two must agree)
        from paddle_tpu.monitor import ledger
        prof_fn = getattr(server, "profile", None)
        prof = prof_fn() if prof_fn is not None else ledger.profile()
        progs = prof.get("programs") or {}
        if progs:
            from tools.monitor_report import render_profile
            print()
            print(render_profile(prof))
            print()
            print(json.dumps({"metric": f"serve_profile_programs{sfx}",
                              "value": len(progs), "unit": "count"}))
            print(json.dumps(
                {"metric": f"serve_profile_dispatch_seconds{sfx}",
                 "value": round(prof.get("total_seconds", 0.0), 6),
                 "unit": "s"}))
        if args.profile_out:
            ppath = args.profile_out + sfx
            with open(ppath, "w") as f:
                json.dump(prof, f, indent=1)
            print(f"wrote /profile snapshot to {ppath} "
                  f"(tools/monitor_report.py --profile {ppath})")
    if server is not None:
        if args.monitor_out:
            from paddle_tpu import monitor
            from paddle_tpu.monitor.provenance import env_stamp
            path = args.monitor_out + sfx
            n = monitor.write_jsonl(path,
                                    extra={"env": env_stamp()})
            print(f"wrote {n} monitor samples to {path}")
        server.shutdown(drain=False)
        if trace_on:
            from paddle_tpu import tracing
            tracing.disable()   # in-process callers (slow-tier tests)
            #                     must not inherit a live recorder
        if getattr(args, "profile", False):
            from paddle_tpu.monitor import ledger
            ledger.disable()    # same contract as tracing above
    return {
        "tpot_p50": (_percentile(stats.tpot, 50) if stats.tpot
                     else None),
        "ttft_p50": (_percentile(stats.ttft, 50) if stats.ttft
                     else None),
        "throughput": (stats.tokens / wall if wall > 0 else None),
        "kv_page_cost": kv_page_cost,
        "model_bytes": model_bytes,
        **extra,
    }


if __name__ == "__main__":
    sys.exit(main())
