"""paddle.metric parity (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    return np.asarray(x)


class Metric(abc.ABC):
    """reference metrics.py Metric: reset/update/accumulate/name contract,
    with compute() as the preprocessing hook Model.fit calls on (pred, label)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """reference metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._init_name(name)
        self.reset()

    def _init_name(self, name):
        name = name or "acc"
        if self.maxk != 1:
            self._name = [f"{name}_top{k}" for k in self.topk]
        else:
            self._name = [name]

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        pred_idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == pred.shape[-1] and label.shape[-1] > 1:
                label = np.argmax(label, axis=-1)  # one-hot → index
            else:
                label = label[..., 0]  # paddle [N,1] index convention
        correct = (pred_idx == label[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        correct = _to_np(correct)
        num_samples = int(np.prod(correct.shape[:-1]))
        accs = []
        for k in self.topk:
            num_corrects = correct[..., :k].sum()
            accs.append(float(num_corrects) / max(num_samples, 1))
            self.total[self.topk.index(k)] += float(num_corrects)
            self.count[self.topk.index(k)] += num_samples
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """Binary precision (reference metrics.py Precision)."""

    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels)
        pred_bin = (preds > 0.5).astype(np.int32).reshape(-1)
        labels = labels.reshape(-1).astype(np.int32)
        self.tp += int(np.sum((pred_bin == 1) & (labels == 1)))
        self.fp += int(np.sum((pred_bin == 1) & (labels == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference metrics.py Recall)."""

    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels)
        pred_bin = (preds > 0.5).astype(np.int32).reshape(-1)
        labels = labels.reshape(-1).astype(np.int32)
        self.tp += int(np.sum((pred_bin == 1) & (labels == 1)))
        self.fn += int(np.sum((pred_bin == 0) & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion histogram (reference metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._curve = curve
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum(
            (pos_prob * self._num_thresholds).astype(np.int64),
            self._num_thresholds - 1)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds, np.int64)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        idx = self._num_thresholds - 1
        while idx >= 0:
            tot_pos_prev = tot_pos
            tot_neg_prev = tot_neg
            tot_pos += self._stat_pos[idx]
            tot_neg += self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, tot_neg_prev, tot_pos,
                                       tot_pos_prev)
            idx -= 1
        return (auc / tot_pos / tot_neg
                if tot_pos > 0.0 and tot_neg > 0.0 else 0.0)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: paddle.metric.accuracy)."""
    pred = _to_np(input)
    lab = _to_np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        if lab.shape[-1] == pred.shape[-1] and lab.shape[-1] > 1:
            lab = np.argmax(lab, axis=-1)  # one-hot
        else:
            lab = lab[..., 0]              # paddle [N,1] index convention
    corr = np.any(idx == lab[..., None], axis=-1)
    return Tensor(np.asarray(corr.mean(), np.float32))
