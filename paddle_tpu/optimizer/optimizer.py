"""Optimizers (python/paddle/optimizer/optimizer.py parity).

TPU-native design: each optimizer's update rule is a PURE function
``_update(param, grad, state, lr) -> (new_param, new_state)`` over jax
arrays. ``step()`` applies it eagerly (optionally under one jit for the whole
parameter list); the same pure rule is reused inside compiled train steps by
paddle_tpu.jit and the distributed sharding optimizers — matching how the
reference shares phi optimizer kernels (phi/kernels/gpu/adam_kernel.cu)
between eager and static executors.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.clip import ClipGradBase
from ..nn.parameter import Parameter
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
           "Adam", "AdamW", "Adamax", "Lamb", "LBFGS"]


class L2Decay:
    """paddle.regularizer.L2Decay — coupled weight decay added to the grad."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, p, g):
        return g + self.coeff * p


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, p, g):
        return g + self.coeff * jnp.sign(p)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # weight_decay: float → L2Decay (reference regularizer semantics)
        if isinstance(weight_decay, (int, float)):
            self._regularization = L2Decay(weight_decay)
        else:
            self._regularization = weight_decay
        # state: name -> {param_name: array}
        self._accumulators: Dict[str, Dict[int, Any]] = {}
        self._step_count = 0
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for group in self._param_groups:
                flat.extend(group["params"])
            self._parameter_list = flat

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when lr is an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    # -- state -------------------------------------------------------------
    def _key(self, p) -> str:
        return p.name if p.name else f"param_{id(p)}"

    def _acc(self, name: str, p, init=None):
        d = self._accumulators.setdefault(name, {})
        k = self._key(p)
        if k not in d:
            d[k] = jnp.zeros_like(p.value) if init is None else init
        return d[k]

    def _set_acc(self, name: str, p, value):
        self._accumulators[name][self._key(p)] = value

    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {}
        for acc_name, d in self._accumulators.items():
            for pkey, v in d.items():
                sd[f"{pkey}_{acc_name}"] = Tensor(v)
        sd["global_step"] = self._step_count
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]):
        if "global_step" in state_dict:
            v = state_dict["global_step"]
            self._step_count = int(v.item() if hasattr(v, "item") else v)
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        # restore into existing accumulator slots
        restored = set()
        for acc_name, d in self._accumulators.items():
            for pkey in list(d.keys()):
                full = f"{pkey}_{acc_name}"
                if full in state_dict:
                    v = state_dict[full]
                    d[pkey] = jnp.asarray(v.value if isinstance(v, Tensor) else v)
                    restored.add(full)
        # a FRESH optimizer has no accumulators yet — match remaining state
        # keys against param names so resume does not silently drop moments
        pkeys = sorted((self._key(p) for p in self._parameter_list or []),
                       key=len, reverse=True)
        for full, v in state_dict.items():
            if full in restored or full in ("global_step", "LR_Scheduler") \
                    or full.startswith("__"):
                continue
            for pkey in pkeys:
                if full.startswith(pkey + "_"):
                    acc_name = full[len(pkey) + 1:]
                    self._accumulators.setdefault(acc_name, {})[pkey] = \
                        jnp.asarray(v.value if isinstance(v, Tensor) else v)
                    break

    set_dict = set_state_dict

    # -- core --------------------------------------------------------------
    def _collect_params_grads(self) -> List[Tuple[Parameter, Optional[Tensor]]]:
        if self._parameter_list is None:
            raise ValueError("optimizer created without parameters")
        return [(p, p.grad) for p in self._parameter_list
                if not getattr(p, "stop_gradient", False) or p.grad is not None]

    def _apply_decay_and_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gv = g.value if isinstance(g, Tensor) else g
            reg = getattr(p, "regularizer", None) or self._regularization
            if reg is not None and not self._decoupled_wd():
                gv = reg(p.value, gv)
            out.append((p, Tensor(gv)))
        if self._grad_clip is not None:
            out = self._grad_clip(out)
        return out

    def _decoupled_wd(self) -> bool:
        return False

    def step(self):
        params_grads = self._apply_decay_and_clip(self._collect_params_grads())
        self._step_count += 1
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else lr
            p._value = self._update_param(p, g.value, p_lr)

    def _update_param(self, p, g, lr):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.program import default_main_program, in_static_mode

        if in_static_mode():
            # static path: mark the program for training; the Executor
            # composes jax.grad + _static_update into the jitted step
            # (≙ append_backward + optimizer ops appended to the ProgramDesc)
            default_main_program().train_config = (self, id(loss))
            return None, None
        loss.backward()
        self.step()
        return None, None

    # -- static-graph functional update (used by static.Executor) ----------
    def _static_update(self, params, grads, opt_state, lr=None):
        """(params, grads, opt_state, lr) → (new_params, opt_state). `lr`
        is a traced value supplied per run (schedulers stay live across the
        cached jit). Default: plain SGD; stateful subclasses override."""
        from .functional import sgd_update

        lr = self.get_lr() if lr is None else lr
        return sgd_update(grads, params, lr=lr), opt_state

    def clear_grad(self, set_to_zero: bool = False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_param(self, p, g, lr):
        return (p.value - lr * g).astype(p.value.dtype)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._rescale = rescale_grad

    def _update_param(self, p, g, lr):
        g = g * self._rescale
        v = self._acc("velocity", p)
        v_new = self._momentum * v + g
        self._set_acc("velocity", p, v_new)
        if self._use_nesterov:
            upd = g + self._momentum * v_new
        else:
            upd = v_new
        return (p.value - lr * upd).astype(p.value.dtype)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr):
        m = self._acc("moment", p,
                      init=jnp.full_like(p.value, self._init_acc))
        m_new = m + g * g
        self._set_acc("moment", p, m_new)
        return (p.value - lr * g / (jnp.sqrt(m_new) + self._epsilon)).astype(p.value.dtype)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, g, lr):
        avg_sq = self._acc("avg_squared_grad", p)
        avg_upd = self._acc("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(avg_upd + self._epsilon) / jnp.sqrt(avg_sq + self._epsilon)
        avg_upd = self._rho * avg_upd + (1 - self._rho) * upd * upd
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_upd)
        return (p.value - lr * upd).astype(p.value.dtype)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr):
        ms = self._acc("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * g * g
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._acc("momentum", p)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc("momentum", p, mom)
        return (p.value - mom).astype(p.value.dtype)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _beta(self, b):
        return float(b) if not isinstance(b, Tensor) else float(b)

    def _static_update(self, params, grads, opt_state, lr=None):
        return _adam_static_update(self, params, grads, opt_state, lr=lr,
                                   weight_decay=0.0)

    def _update_param(self, p, g, lr):
        b1, b2 = self._beta(self._beta1), self._beta(self._beta2)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b1p = b1p * b1
        b2p = b2p * b2
        gf = g.astype(jnp.float32) if g.dtype != jnp.float32 else g
        pf = p.value.astype(jnp.float32)
        m = b1 * m + (1 - b1) * gf
        v = b2 * v + (1 - b2) * gf * gf
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        new_p = pf - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        return new_p.astype(p.value.dtype)


def _adam_static_update(opt, params, grads, opt_state, lr=None,
                        weight_decay=0.0):
    from .functional import adamw_init, adamw_update

    if opt_state is None:
        opt_state = adamw_init(params)
    lr = opt.get_lr() if lr is None else lr
    new_state, new_params = adamw_update(
        grads, opt_state, params, lr=lr, beta1=opt._beta(opt._beta1),
        beta2=opt._beta(opt._beta2), epsilon=opt._epsilon,
        weight_decay=weight_decay)
    return new_params, new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name=name)
        self._wd_coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decoupled_wd(self):
        return True

    def _static_update(self, params, grads, opt_state, lr=None):
        return _adam_static_update(self, params, grads, opt_state, lr=lr,
                                   weight_decay=self._wd_coeff)

    def _update_param(self, p, g, lr):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        decay = self._wd_coeff
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(
                self._key(p)):
            decay = 0.0
        # decoupled decay BEFORE the adam update (reference adamw kernel order)
        pv = p.value.astype(jnp.float32) * (1.0 - lr * decay)
        b1, b2 = self._beta(self._beta1), self._beta(self._beta2)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b1p = b1p * b1
        b2p = b2p * b2
        gf = g.astype(jnp.float32) if g.dtype != jnp.float32 else g
        m = b1 * m + (1 - b1) * gf
        v = b2 * v + (1 - b2) * gf * gf
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        new_p = pv - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        return new_p.astype(p.value.dtype)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, g, lr):
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b1p = b1p * self._beta1
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        self._set_acc("beta1_pow", p, b1p)
        return (p.value - lr / (1 - b1p) * m / (u + self._epsilon)).astype(p.value.dtype)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.asarray(1.0, jnp.float32))
        b1p = b1p * self._beta1
        b2p = b2p * self._beta2
        gf = g.astype(jnp.float32)
        pf = p.value.astype(jnp.float32)
        m = self._beta1 * m + (1 - self._beta1) * gf
        v = self._beta2 * v + (1 - self._beta2) * gf * gf
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._wd
        upd = r + wd * pf
        w_norm = jnp.linalg.norm(pf)
        u_norm = jnp.linalg.norm(upd)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        return (pf - lr * trust * upd).astype(p.value.dtype)


class LBFGS(Optimizer):
    """Limited-memory BFGS with strong-Wolfe line search
    (python/paddle/optimizer/lbfgs.py parity; host-driven loop — not a jit
    target, matching the reference's Python implementation)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        self._line_search_fn = line_search_fn
        self._state = {"old_dirs": [], "old_stps": [], "ro": [], "prev_flat_grad": None,
                       "H_diag": 1.0, "n_iter": 0, "d": None, "t": None}

    def _gather_flat_grad(self):
        return jnp.concatenate([
            (p.grad.value if p.grad is not None else jnp.zeros_like(p.value)).reshape(-1)
            for p in self._parameter_list])

    def _add_to_params(self, step_size, direction):
        offset = 0
        for p in self._parameter_list:
            n = p.value.size
            p._value = (p.value + step_size * direction[offset:offset + n]
                        .reshape(p.value.shape)).astype(p.value.dtype)
            offset += n

    def step(self, closure=None):
        if closure is None:
            raise RuntimeError("LBFGS.step requires a closure returning the loss")
        st = self._state
        loss = closure()
        flat_grad = self._gather_flat_grad()
        if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
            return loss
        n_evals = 1
        for _ in range(self._max_iter):
            st["n_iter"] += 1
            if st["n_iter"] == 1:
                d = -flat_grad
                H_diag = 1.0
            else:
                y = flat_grad - st["prev_flat_grad"]
                s = st["d"] * st["t"]
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(st["old_dirs"]) >= self._history_size:
                        st["old_dirs"].pop(0)
                        st["old_stps"].pop(0)
                        st["ro"].pop(0)
                    st["old_dirs"].append(y)
                    st["old_stps"].append(s)
                    st["ro"].append(1.0 / ys)
                    H_diag = ys / float(y @ y)
                else:
                    H_diag = st["H_diag"]
                # two-loop recursion
                q = -flat_grad
                alphas = []
                for s_i, y_i, ro_i in zip(reversed(st["old_stps"]),
                                          reversed(st["old_dirs"]),
                                          reversed(st["ro"])):
                    a = ro_i * float(s_i @ q)
                    alphas.append(a)
                    q = q - a * y_i
                d = q * H_diag
                for (s_i, y_i, ro_i), a in zip(
                        zip(st["old_stps"], st["old_dirs"], st["ro"]),
                        reversed(alphas)):
                    b = ro_i * float(y_i @ d)
                    d = d + s_i * (a - b)
            st["prev_flat_grad"] = flat_grad
            st["H_diag"] = H_diag
            t = self.get_lr() if st["n_iter"] > 1 else min(
                1.0, 1.0 / float(jnp.sum(jnp.abs(flat_grad)))) * self.get_lr()
            gtd = float(flat_grad @ d)
            self._add_to_params(t, d)
            st["d"], st["t"] = d, t
            loss = closure()
            flat_grad = self._gather_flat_grad()
            n_evals += 1
            if n_evals >= self._max_eval:
                break
            if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
                break
            if float(jnp.max(jnp.abs(d * t))) <= self._tol_change:
                break
        return loss
