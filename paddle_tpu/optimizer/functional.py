"""Functional optimizer transforms for jitted/pjit-ed training steps.

The eager ``paddle_tpu.optimizer.*`` classes are imperative (reference
parity); compiled training wants pure (state, grads) -> (state, updates)
transforms so the whole step — forward, backward, clip, update — is ONE
XLA program with donated buffers. These mirror the math of the eager
classes (reference optimizer semantics: python/paddle/optimizer/adamw.py)
and are what __graft_entry__/bench use.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["adamw_init", "adamw_update", "sgd_update", "clip_by_global_norm",
           "AdamWState"]


class AdamWState(NamedTuple):
    step: Any
    m: Any     # first moment, per-param pytree
    v: Any     # second moment, per-param pytree


def adamw_init(params, master_dtype=jnp.float32,
               moment_dtype=None) -> AdamWState:
    # moment_dtype (e.g. bf16) applies to the FIRST moment only: m changes
    # by 1-beta1 = 0.1 per step, well above the bf16 ulp. v must stay
    # fp32 — its per-step relative change (1-beta2 = 0.001) is below the
    # bf16 ulp (~0.004), so a bf16 store would round every update away and
    # freeze v permanently (verified numerically: stuck at its warm-up
    # value). m-only bf16 still cuts optimizer HBM by 25% (the 1.3B-on-
    # one-chip policy together with the smaller batch).
    moment_dtype = moment_dtype or master_dtype
    z = lambda t, dt: jax.tree.map(lambda p: jnp.zeros(p.shape, dt), t)
    return AdamWState(step=jnp.zeros((), jnp.int32),
                      m=z(params, moment_dtype),
                      v=z(params, master_dtype))


def adamw_update(grads, state: AdamWState, params, lr=1e-3, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, weight_decay=0.01,
                 master_dtype=jnp.float32):
    """One AdamW step. Update math accumulates in master_dtype (fp32);
    moments are stored back in whatever dtype adamw_init chose — bf16
    moments (moment_dtype) halve optimizer HBM with fp32 math intact."""
    step = state.step + 1
    c1 = 1.0 - beta1 ** step.astype(jnp.float32)
    c2 = 1.0 - beta2 ** step.astype(jnp.float32)

    def upd(g, m, v, p):
        mdt, vdt = m.dtype, v.dtype
        g32 = g.astype(master_dtype)
        m = beta1 * m.astype(master_dtype) + (1 - beta1) * g32
        v = beta2 * v.astype(master_dtype) + (1 - beta2) * (g32 * g32)
        mhat = m / c1
        vhat = v / c2
        delta = mhat / (jnp.sqrt(vhat) + epsilon) + weight_decay * p.astype(master_dtype)
        return (m.astype(mdt), v.astype(vdt),
                (p.astype(master_dtype) - lr * delta).astype(p.dtype))

    flat_g, tdef = jax.tree.flatten(grads)
    flat_m = tdef.flatten_up_to(state.m)
    flat_v = tdef.flatten_up_to(state.v)
    flat_p = tdef.flatten_up_to(params)
    new_m, new_v, new_p = [], [], []
    for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p):
        m2, v2, p2 = upd(g, m, v, p)
        new_m.append(m2)
        new_v.append(v2)
        new_p.append(p2)
    return (AdamWState(step=step, m=tdef.unflatten(new_m),
                       v=tdef.unflatten(new_v)),
            tdef.unflatten(new_p))


def sgd_update(grads, params, lr=0.01, weight_decay=0.0):
    def upd(g, p):
        d = g + weight_decay * p
        return (p - lr * d).astype(p.dtype)

    return jax.tree.map(upd, grads, params)


def clip_by_global_norm(grads, clip_norm: float):
    leaves = jax.tree.leaves(grads)
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    gnorm = jnp.sqrt(total)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                        grads), gnorm
