"""paddle.signal parity (reference: python/paddle/signal.py — stft/istft
built on frame/overlap_add ops)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op
from .ops._helpers import unwrap

__all__ = ["stft", "istft"]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (signal.py stft). x: [B, T] or [T].
    Returns [B, n_fft(/2+1), num_frames] complex."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    wv = unwrap(window) if window is not None else None

    def f(v, *maybe_w):
        w = maybe_w[0] if maybe_w else None
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if w is None:
            w = jnp.ones((wl,), v.dtype)
        if wl < n_fft:  # center-pad window to n_fft
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        if center:
            v = jnp.pad(v, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        t = v.shape[-1]
        n_frames = 1 + (t - n_fft) // hop
        idx = (jnp.arange(n_frames)[:, None] * hop
               + jnp.arange(n_fft)[None, :])
        frames = v[:, idx] * w[None, None, :]          # [B, F, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))     # [B, F, bins]
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)               # [B, bins, F]
        return out[0] if squeeze else out

    args = (x, window) if window is not None else (x,)
    return apply_op(f, *args, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT via overlap-add (signal.py istft)."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft

    def f(v, *maybe_w):
        w = maybe_w[0] if maybe_w else None
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        if w is None:
            w = jnp.ones((wl,), jnp.float32)
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        spec = jnp.swapaxes(v, -1, -2)                 # [B, F, bins]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)  # [B, F, n_fft]
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w[None, None, :]
        b, nf, _ = frames.shape
        t_len = n_fft + hop * (nf - 1)
        out = jnp.zeros((b, t_len), frames.dtype)
        wsum = jnp.zeros((t_len,), frames.dtype)
        idx = (jnp.arange(nf)[:, None] * hop + jnp.arange(n_fft)[None, :])
        out = out.at[:, idx].add(frames)
        wsum = wsum.at[idx].add((w * w)[None, :].repeat(nf, 0))
        out = out / jnp.maximum(wsum, 1e-11)[None]
        if center:
            # with an explicit length, only the LEFT half-window is
            # trimmed — the right-edge samples (reconstructed from the
            # centering pad) satisfy the requested length (the
            # reference/torch contract); without it both halves go
            if length is not None:
                out = out[:, n_fft // 2:]
            else:
                out = out[:, n_fft // 2: t_len - n_fft // 2]
        if length is not None:
            if out.shape[-1] < length:  # trimmed OR zero-padded exactly
                out = jnp.pad(out, ((0, 0),
                                    (0, length - out.shape[-1])))
            out = out[:, :length]
        return out[0] if squeeze else out

    args = (x, window) if window is not None else (x,)
    return apply_op(f, *args, op_name="istft")
