"""Per-backend peak compute/bandwidth table — the roofline's ceiling.

The program ledger (``paddle_tpu.monitor.ledger``) turns XLA
``cost_analysis()`` FLOPs/bytes plus measured dispatch time into
achieved FLOP/s and bytes/s; THIS module supplies the denominator —
the peak the hardware could do — so MFU and the roofline verdict
(memory-bound vs compute-bound) mean the same thing across backends:

- **TPU**: a static per-generation table keyed by substring match on
  ``device_kind`` (bf16 dense peaks + HBM bandwidth). The v4/v5 compute
  numbers intentionally match the ones ``bench.py`` has used for every
  recorded ``BENCH_r*.json`` MFU, so ledger MFU and the training-bench
  MFU stay comparable across rounds.
- **CPU** (the tier-1/test backend): no meaningful datasheet number
  exists, so the peak is CALIBRATED once per process — a small timed
  matmul for FLOP/s, a timed device-array copy for bytes/s — and
  cached. Calibrated MFU is only comparable within one host, which is
  exactly what a CPU A/B needs (and why the record carries
  ``source: "calibrated"``).
- Environment overrides ``PADDLE_TPU_PEAK_FLOPS`` /
  ``PADDLE_TPU_PEAK_BYTES`` win over both (``source: "env"``) — the
  escape hatch for unlisted hardware or a deliberately pinned baseline.

``machine_balance`` (peak FLOPs / peak bytes, FLOP-per-byte) is the
roofline ridge: a program whose arithmetic intensity sits below it is
memory-bound — more MXU would not help; feeding it would.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["peaks", "peak_flops", "machine_balance", "TPU_PEAKS"]

# (device_kind substring, bf16 dense FLOP/s, HBM bytes/s) — first match
# wins, so more specific generations sort before catch-alls ("v5e"
# before "v5"; device_kind examples: "TPU v4", "TPU v5e", "TPU v5p",
# "TPU v6e"/"TPU Trillium").
TPU_PEAKS = (
    ("v6e", 918e12, 1640e9),
    ("trillium", 918e12, 1640e9),
    ("v5e", 394e12, 819e9),
    ("lite", 394e12, 819e9),
    ("v5", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)

_lock = threading.Lock()
_cache: Optional[Dict[str, Any]] = None


def _calibrate_cpu() -> Dict[str, float]:
    """One-shot CPU peak probe: best-of-3 timed f32 matmul (2·n³ FLOPs)
    and device-array copy (2·nbytes moved). ~100 ms once per process;
    runs at ledger enable / first profile read, never on a dispatch."""
    import jax
    import jax.numpy as jnp

    n = 512
    x = jnp.ones((n, n), jnp.float32)
    # lint: allow-recompile(one-shot probe, result cached per process)
    mm = jax.jit(lambda a: a @ a)
    # lint: allow-recompile(one-shot probe, result cached per process)
    cp = jax.jit(lambda a: a + 0.0)
    mm(x).block_until_ready()           # compile outside the clock
    cp(x).block_until_ready()
    best_mm = best_cp = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        mm(x).block_until_ready()
        best_mm = min(best_mm, time.perf_counter() - t0)
        t0 = time.perf_counter()
        cp(x).block_until_ready()
        best_cp = min(best_cp, time.perf_counter() - t0)
    flops = 2.0 * n ** 3 / max(best_mm, 1e-9)
    byts = 2.0 * x.nbytes / max(best_cp, 1e-9)   # read + write
    return {"peak_flops": flops, "peak_bytes_per_s": byts}


def peaks(refresh: bool = False) -> Dict[str, Any]:
    """The backend peak record, cached per process::

        {"device_kind", "platform", "peak_flops", "peak_bytes_per_s",
         "machine_balance", "source": "table" | "calibrated" | "env"}

    Never raises: with no usable backend it falls back to a nominal
    1 TFLOP/s (``source: "fallback"``) so a profile read cannot take
    serving down."""
    global _cache
    with _lock:
        if _cache is not None and not refresh:
            return _cache
    kind, platform = "unknown", "unknown"
    flops = byts = None
    source = "fallback"
    try:
        import jax

        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", platform) or platform
        low = kind.lower()
        for sub, f, b in TPU_PEAKS:
            if sub in low:
                flops, byts, source = f, b, "table"
                break
        if flops is None:
            cal = _calibrate_cpu()
            flops = cal["peak_flops"]
            byts = cal["peak_bytes_per_s"]
            source = "calibrated"
    except Exception:
        pass
    env_f = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    env_b = os.environ.get("PADDLE_TPU_PEAK_BYTES")
    if env_f or env_b:
        source = "env"
        if env_f:
            flops = float(env_f)
        if env_b:
            byts = float(env_b)
    if not flops or flops <= 0:
        flops = 1e12
    if not byts or byts <= 0:
        byts = 1e11
    rec = {"device_kind": kind, "platform": platform,
           "peak_flops": flops, "peak_bytes_per_s": byts,
           "machine_balance": flops / byts, "source": source}
    with _lock:
        _cache = rec
    return rec


def peak_flops() -> float:
    """Shorthand for ``peaks()["peak_flops"]``."""
    return peaks()["peak_flops"]


def machine_balance() -> float:
    """The roofline ridge point in FLOP/byte: programs below it are
    memory-bound on this backend, above it compute-bound."""
    return peaks()["machine_balance"]
