"""paddle.device parity (reference: python/paddle/device/__init__.py).

Device management over the JAX runtime: the reference's cuda/xpu split
maps to TPU-first with CPU fallback; CUDA-only knobs exist as honest
no-ops/gates so reference scripts run unmodified.
"""
from __future__ import annotations

from ..core.place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace,
                          device_count, get_device, is_compiled_with_cuda,
                          is_compiled_with_tpu, set_device)
from . import cuda, peaks, xpu
from .cuda import Event, Stream, current_stream, stream_guard

__all__ = ["get_device", "set_device", "get_all_device_type", "peaks",
           "get_all_custom_device_type", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cuda",
           "is_compiled_with_tpu", "is_compiled_with_xpu",
           "is_compiled_with_cinn", "is_compiled_with_rocm", "cuda", "xpu",
           "synchronize", "XPUPlace", "IPUPlace", "Stream", "Event",
           "current_stream", "stream_guard", "set_stream",
           "get_cudnn_version"]


def get_cudnn_version():
    """None — no cuDNN in a TPU build (reference returns the version int
    or None when CUDA is absent)."""
    return None


def set_stream(stream=None):
    """Reference device.set_stream: XLA owns stream scheduling; accepts
    and returns the stream for API compatibility."""
    return stream or current_stream()


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role (DESIGN.md); the reference flag answers "is
    # the optional tensor-compiler path built in" — here it always is
    return True


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax

    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def synchronize(device=None):
    """Block until all queued device work finishes (reference
    device.synchronize / cuda.synchronize)."""
    import jax

    (jax.device_put(0) + 0).block_until_ready()


class XPUPlace:  # API-parity placeholder
    def __init__(self, dev_id=0):
        raise RuntimeError("XPU is not available in a TPU-native build")


class IPUPlace:
    def __init__(self, dev_id=0):
        raise RuntimeError("IPU is not available in a TPU-native build")
