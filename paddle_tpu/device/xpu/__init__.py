"""paddle.device.xpu parity — gated (no XPU in a TPU-native build)."""

__all__ = ["synchronize"]


def synchronize(device=None):
    raise RuntimeError("XPU is not available in a TPU-native build")
