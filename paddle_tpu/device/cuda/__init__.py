"""paddle.device.cuda parity (reference: python/paddle/device/cuda/).

On TPU these resolve against the JAX runtime where meaningful and are
honest no-ops where the concept is CUDA-specific (streams and caching
allocator belong to XLA here).
"""
from __future__ import annotations

__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "empty_cache", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "stream_guard", "get_device_properties", "get_device_name",
           "get_device_capability"]


def device_count() -> int:
    import jax

    return sum(1 for d in jax.devices() if d.platform != "cpu") or 0


def synchronize(device=None):
    from .. import synchronize as _sync

    _sync(device)


def empty_cache():
    """XLA owns the allocator; nothing to flush eagerly."""


def _mem_stat(key: str, device=None) -> int:
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        return 0
    stats = devs[0].memory_stats() or {}
    return int(stats.get(key, 0))


def memory_allocated(device=None) -> int:
    return _mem_stat("bytes_in_use", device)


def max_memory_allocated(device=None) -> int:
    return _mem_stat("peak_bytes_in_use", device)


def memory_reserved(device=None) -> int:
    return _mem_stat("bytes_reserved", device) or _mem_stat(
        "bytes_in_use", device)


def max_memory_reserved(device=None) -> int:
    return max_memory_allocated(device)


def get_device_name(device=None) -> str:
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return devs[0].device_kind if devs else "cpu"


def get_device_capability(device=None):
    return (0, 0)  # CUDA compute capability has no TPU analog


def get_device_properties(device=None):
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        raise RuntimeError("no accelerator device present")
    d = devs[0]

    class _Props:
        name = d.device_kind
        major, minor = 0, 0
        total_memory = (d.memory_stats() or {}).get("bytes_limit", 0)
        multi_processor_count = 1

    return _Props()


class Stream:
    """CUDA-stream shim: XLA orders work per device; the API exists so
    reference code constructs/queries it without branching."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        synchronize()

    def wait_stream(self, stream):
        synchronize()

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None) -> Stream:
    return Stream(device)


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False
