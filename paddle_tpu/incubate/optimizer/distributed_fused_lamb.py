"""DistributedFusedLamb (reference: python/paddle/incubate/optimizer/
distributed_fused_lamb.py:111 + distributed_fused_lamb_op.cu).

The reference fuses the whole LAMB update across all parameters into a few
CUDA kernels over flattened fp16/fp32 buffers with moments SHARDED across
dp ranks. The TPU-native translation:

- kernel fusion is XLA's job — the update is expressed once over the whole
  parameter pytree and compiles to a fused program;
- the moment sharding maps to the ZeRO ``sharding`` mesh axis: when a
  global mesh with a live sharding axis exists, moments are placed with
  ``state_pspec`` (the same placement the fleet sharded optimizer uses);
- ``clip_after_allreduce`` keeps its meaning: under SPMD the gradient IS
  post-allreduce, so True (default) clips the logical global grad; False
  is accepted for API parity.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Lamb

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(
            learning_rate=learning_rate,
            lamb_weight_decay=lamb_weight_decay, beta1=beta1, beta2=beta2,
            epsilon=epsilon, parameters=parameters, grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
            name=name)
        self._clip_after_allreduce = clip_after_allreduce
        self._is_grad_scaled_by_nranks = is_grad_scaled_by_nranks
        self._acc_steps = max(1, int(gradient_accumulation_steps))
        self._acc_count = 0
        self._grad_bank = {}
        self._states_sharded = False

    # -- ZeRO placement of moments over the sharding axis ------------------
    def _shard_states(self):
        if self._states_sharded:
            return
        from ...distributed.topology import get_mesh

        mesh = get_mesh()
        if mesh is None or "sharding" not in mesh.axis_names \
                or mesh.shape.get("sharding", 1) <= 1:
            # keep retrying: the mesh may come up after the first step
            return
        self._states_sharded = True
        from jax.sharding import NamedSharding

        from ...distributed._spmd import _filter_spec
        from ...distributed.sharding.sharded_optimizer import state_pspec

        from jax.sharding import PartitionSpec as P

        by_key = {self._key(p): p for p in self._parameter_list or []}
        for acc_name, d in self._accumulators.items():
            for pkey, v in d.items():
                p = by_key.get(pkey)
                if p is None:
                    continue
                spec = _filter_spec(state_pspec(p, mesh), mesh)
                if len(spec) > getattr(v, "ndim", 0):
                    spec = P()  # scalar accumulators (beta pows) replicate
                d[pkey] = jax.device_put(v, NamedSharding(mesh, spec))

    def step(self):
        self._acc_count += 1
        if self._acc_steps > 1:
            pgs = self._collect_params_grads()
            for p, g in pgs:
                if g is None:
                    continue
                k = self._key(p)
                g32 = g.value.astype(jnp.float32)
                prev = self._grad_bank.get(k)
                self._grad_bank[k] = g32 if prev is None else prev + g32
            if self._acc_count % self._acc_steps:
                self.clear_grad()
                return
            from ...core.tensor import Tensor

            for p, g in pgs:
                k = self._key(p)
                if k in self._grad_bank:
                    p.grad = Tensor(
                        (self._grad_bank[k] / self._acc_steps).astype(
                            p.value.dtype))
            self._grad_bank.clear()
        super().step()
        self._shard_states()
