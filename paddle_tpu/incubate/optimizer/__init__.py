"""paddle.incubate.optimizer parity."""
from .distributed_fused_lamb import DistributedFusedLamb

__all__ = ["DistributedFusedLamb"]
