"""paddle.incubate parity namespace (reference: python/paddle/incubate/)."""
import importlib

_LAZY = {"distributed", "nn", "asp", "optimizer", "autograd"}
_API = ("segment_sum", "segment_mean", "segment_min", "segment_max",
        "graph_send_recv", "graph_reindex", "graph_sample_neighbors",
        "graph_khop_sampler", "softmax_mask_fuse",
        "softmax_mask_fuse_upper_triangle", "identity_loss",
        "LookAhead", "ModelAverage")


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _API:
        mod = importlib.import_module("._api", __name__)
        for n in _API:
            globals()[n] = getattr(mod, n)
        return globals()[name]
    raise AttributeError(f"module 'paddle_tpu.incubate' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _LAZY | set(_API))
