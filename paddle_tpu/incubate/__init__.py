"""paddle.incubate parity namespace (reference: python/paddle/incubate/)."""
import importlib

_LAZY = {"distributed", "nn", "asp", "optimizer"}


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.incubate' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _LAZY)
