"""Mixture-of-Experts layer with expert parallelism.

Reference: incubate/distributed/models/moe/moe_layer.py:263 (MoELayer,
forward :405) built on MoEScatter/MoEGather PyLayers (:99,149) over the CUDA
``global_scatter/global_gather`` all-to-all ops
(fluid/operators/collective/global_scatter_op.cu.cc).

TPU-native redesign (GShard/Mesh-TF formulation): routing is DENSE algebra —
a capacity-bucketed dispatch tensor [T, E, C] built from the gate's top-k
choices with a cumsum position assignment, applied as einsums:

    expert_in  = einsum('tec,td->ecd', dispatch, x)
    expert_out = f_e(expert_in[e])            (per-expert FFN)
    y          = einsum('tec,ecd->td', combine, expert_out)

Under a mesh with an ``ep`` axis the e dim of expert_in/out is sharded
(P("ep")), so the two einsums lower to the SAME all-to-all the reference's
global_scatter/gather launch — inserted by XLA over ICI instead of NCCL.
Everything is static-shaped (capacity pads/drops), so the whole layer jits.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from .....core.autograd import apply_op
from .....distributed._spmd import P, constraint
from .....nn.layer.container import LayerList
from .....nn.layer.layers import Layer

__all__ = ["MoELayer", "moe_dispatch", "moe_combine",
           "moe_dispatch_sorted", "moe_combine_sorted"]


def _build_dispatch(idx, val, num_expert: int, capacity: int):
    """Position-assign tokens to experts (GShard cumsum trick).

    idx: [T, k] expert choice per token (int, -1 = dropped)
    val: [T, k] routing weight per choice
    Returns dispatch [T, E, C] bool, combine [T, E, C] float32.
    """
    T, k = idx.shape
    counts = jnp.zeros((num_expert,), jnp.int32)
    disp = jnp.zeros((T, num_expert, capacity), jnp.bool_)
    comb = jnp.zeros((T, num_expert, capacity), jnp.float32)
    # val must be probability-like (gates emit softmaxed weights)
    val = _normalized_weights(idx, val)
    for j in range(k):  # k is tiny and static
        e = idx[:, j]
        onehot = jax.nn.one_hot(e, num_expert, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based rank in expert
        pos = pos + counts[None, :] * onehot       # offset by prior-k fill
        counts = counts + jnp.sum(onehot, axis=0)
        kept = (pos > 0) & (pos <= capacity)
        c = jnp.clip(jnp.sum(pos, axis=1) - 1, 0, capacity - 1)  # [T]
        t_kept = jnp.any(kept, axis=1)
        sel = jax.nn.one_hot(c, capacity, dtype=jnp.float32) * t_kept[:, None]
        contrib = onehot.astype(jnp.float32)[:, :, None] * sel[:, None, :]
        disp = disp | (contrib > 0)
        comb = comb + contrib * val[:, j][:, None, None]
    return disp, comb


def moe_dispatch(x, idx, val, num_expert: int, capacity: int):
    """x:[T,d] → expert_in:[E,C,d] (+ combine for the return trip)."""
    disp, comb = _build_dispatch(idx, val, num_expert, capacity)
    expert_in = jnp.einsum("tec,td->ecd", disp.astype(x.dtype), x)
    return expert_in, comb


def moe_combine(expert_out, comb, dtype):
    return jnp.einsum("tec,ecd->td", comb.astype(expert_out.dtype),
                      expert_out).astype(dtype)


def _normalized_weights(idx, val):
    """Shared by both dispatch paths: zero dropped choices (idx < 0) and
    renormalise over the kept ones (capacity drops do NOT renormalise —
    GShard loses that probability mass, and so do we, identically)."""
    val = jnp.where(idx >= 0, val.astype(jnp.float32), 0.0)
    denom = jnp.sum(val, axis=-1, keepdims=True)
    return val / jnp.maximum(denom, 1e-9)


def _sort_dispatch_plan(idx, val, num_expert: int, capacity: int):
    """Capacity assignment via segment sort — O(T·k) index arrays instead
    of the dense path's [T, E, C] one-hots (VERDICT r4 #7; reference CUDA
    analog: fluid/operators/collective/global_scatter_op.cu.cc routes
    with index buffers, phi/kernels/fusion/cutlass/moe_kernel.cu sorts).

    Token ranking is IDENTICAL to ``_build_dispatch``: that path fills
    each expert with all j=0 choices (in token order) before j=1, so the
    flat (choice-major, then token) order sorted STABLY by expert id
    reproduces the exact same keep/drop set.

    Returns (t, w, slot, kept) over the T·k flat (token, choice) pairs:
    ``slot`` is the destination row in the [E*C, d] expert buffer (an
    out-of-range sentinel for drops — scatter/gather drop/fill modes
    handle it), ``w`` the combine weight.
    """
    T, k = idx.shape
    val = _normalized_weights(idx, val)
    e = idx.T.reshape(-1)                         # choice-major flatten
    t = jnp.tile(jnp.arange(T, dtype=jnp.int32), k)
    w = val.T.reshape(-1)
    ekey = jnp.where(e >= 0, e, num_expert).astype(jnp.int32)
    order = jnp.argsort(ekey, stable=True)
    es, ts, ws = ekey[order], t[order], w[order]
    counts = jnp.bincount(ekey, length=num_expert + 1)
    starts = jnp.cumsum(counts) - counts          # exclusive prefix
    pos = jnp.arange(T * k, dtype=jnp.int32) - starts[es].astype(jnp.int32)
    kept = (es < num_expert) & (pos < capacity)
    slot = jnp.where(kept, es * capacity + pos, num_expert * capacity)
    return ts, ws, slot, kept


def moe_dispatch_sorted(x, idx, val, num_expert: int, capacity: int):
    """Sort-based dispatch: same contract as ``moe_dispatch`` but the
    return-trip state is the O(T·k) plan, not a [T, E, C] tensor."""
    ts, ws, slot, kept = _sort_dispatch_plan(idx, val, num_expert, capacity)
    d = x.shape[-1]
    vals = x[ts] * kept[:, None].astype(x.dtype)
    flat = jnp.zeros((num_expert * capacity, d), x.dtype)
    flat = flat.at[slot].set(vals, mode="drop")   # sentinel rows dropped
    return flat.reshape(num_expert, capacity, d), (ts, ws, slot, kept)


def _pick_dispatch_mode(num_tokens: int, num_expert: int,
                        capacity: int) -> str:
    """auto-mode policy: the dense path materialises TWO [T, E, C]
    fp32/bool tensors; past ~64 MB switch to the sort plan (O(T·k)
    index arrays)."""
    return ("sort" if num_tokens * num_expert * capacity > (1 << 24)
            else "dense")


def moe_combine_sorted(expert_out, ts, ws, slot, kept, num_tokens: int,
                       dtype):
    e, c, d = expert_out.shape
    eo = expert_out.reshape(e * c, d)
    contrib = jnp.take(eo, slot, axis=0, mode="fill", fill_value=0)
    wk = (ws * kept).astype(eo.dtype)
    y = jnp.zeros((num_tokens, d), eo.dtype)
    return y.at[ts].add(contrib * wk[:, None]).astype(dtype)


class MoELayer(Layer):
    """reference moe_layer.py:263 parity.

    Args mirror the reference: ``d_model``, ``experts`` (list of per-expert
    Layers), ``gate`` (a BaseGate or dict config), ``moe_group`` (expert-
    parallel group ≙ the ``ep`` mesh axis), ``recompute_interval``.
    """

    def __init__(self, d_model: int, experts: Optional[List[Layer]] = None,
                 gate=None, moe_group=None, mp_group=None,
                 recompute_interval: int = 0, capacity_factor: float = 1.2,
                 dispatch_mode: str = "auto", **kwargs):
        super().__init__()
        if dispatch_mode not in ("auto", "dense", "sort"):
            raise ValueError(
                f"dispatch_mode={dispatch_mode!r}: expected auto|dense|sort")
        self.dispatch_mode = dispatch_mode
        self.d_model = d_model
        if experts is None:
            raise ValueError("experts list is required")
        self.experts = (experts if isinstance(experts, LayerList)
                        else LayerList(list(experts)))
        self.num_expert = len(self.experts)
        self.capacity_factor = capacity_factor
        self.moe_group = moe_group
        self.recompute_interval = recompute_interval
        if gate is None:
            gate = {"type": "gshard"}
        if isinstance(gate, dict):
            from .gate import GShardGate, NaiveGate, SwitchGate

            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", 2)
            if gtype == "naive":
                gate = NaiveGate(d_model, self.num_expert, topk=topk)
            elif gtype == "gshard":
                # forward top_k so a non-2 request FAILS (GShardGate is
                # top-2 by construction) instead of silently routing top-2
                gate = GShardGate(d_model, self.num_expert, topk=topk)
            elif gtype == "switch":
                gate = SwitchGate(d_model, self.num_expert, topk=1)
            else:
                raise ValueError(f"unknown gate type {gtype}")
        self.gate = gate
        # expert params live on the ep axis: tag each expert's params with
        # its expert id so a stacked/sharded layout can be derived
        for e_id, exp in enumerate(self.experts):
            for _, p in exp.named_parameters():
                p.is_distributed = True

    def forward(self, inp):
        orig_shape = inp.shape
        d = orig_shape[-1]
        x = inp.reshape([-1, d])
        T = x.shape[0]
        E = self.num_expert
        # GShard convention: expected assignments per expert under balanced
        # top-k routing are k*T/E, so capacity must scale with the gate's
        # top-k (reference gshard_gate.py:68 limit_by_capacity) — a plain
        # ceil(cf*T/E) with top-2 would silently drop ~40% of routed tokens
        topk = getattr(self.gate, "top_k",
                       getattr(self.gate, "topk", 1)) or 1
        capacity = max(1, int(math.ceil(
            self.capacity_factor * topk * T / E)))

        val, idx = self.gate(x)

        mode = self.dispatch_mode
        if mode == "auto":
            mode = _pick_dispatch_mode(T, E, capacity)

        if mode == "sort":
            def dispatch_fn(xv, vv, iv):
                return moe_dispatch_sorted(xv, iv, vv, E, capacity)

            expert_in, plan = apply_op(dispatch_fn, x, val, idx.detach(),
                                       op_name="moe_dispatch")
        else:
            # dispatch: [T,d] -> [E,C,d]; combine weights [T,E,C]
            def dispatch_fn(xv, vv, iv):
                return moe_dispatch(xv, iv, vv, E, capacity)

            expert_in, comb = apply_op(dispatch_fn, x, val, idx.detach(),
                                       op_name="moe_dispatch")
        # ep placement: expert dim sharded over the mesh's ep axis → the
        # einsum above lowers to all-to-all over ICI
        expert_in = constraint(expert_in, P("ep"))

        outs = []
        for e in range(E):
            outs.append(self.experts[e](expert_in[e]))
        stacked = outs[0].stack(outs) if hasattr(outs[0], "stack") else None
        if stacked is None:
            import paddle_tpu as _p

            stacked = _p.stack(outs, axis=0)
        stacked = constraint(stacked, P("ep"))

        if mode == "sort":
            def combine_fn(eo, ts, ws, slot, kept):
                return moe_combine_sorted(eo, ts, ws, slot, kept, T,
                                          eo.dtype)

            y = apply_op(combine_fn, stacked, *plan,
                         op_name="moe_combine")
        else:
            def combine_fn(eo, cw):
                return moe_combine(eo, cw, eo.dtype)

            y = apply_op(combine_fn, stacked, comb, op_name="moe_combine")
        return y.reshape(list(orig_shape))
