"""Gate base class (reference: incubate/distributed/models/moe/gate/base_gate.py)."""
from __future__ import annotations

from ......nn.layer.layers import Layer

__all__ = ["BaseGate"]


class BaseGate(Layer):
    def __init__(self, num_expert: int, world_size: int):
        super().__init__()
        self.world_size = max(int(world_size), 1)
        self.num_expert = int(num_expert)
        self.tot_expert = self.world_size * self.num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError("Base gate cannot be called")

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss
