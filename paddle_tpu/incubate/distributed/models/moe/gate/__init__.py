from .base_gate import BaseGate
from .naive_gate import NaiveGate
from .gshard_gate import GShardGate
from .switch_gate import SwitchGate

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]
