"""Naive top-k gate (reference: moe/gate/naive_gate.py — a Linear scorer
with top-k selection, no auxiliary loss)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......core.autograd import apply_op
from ......nn.layer.common import Linear
from .base_gate import BaseGate

__all__ = ["NaiveGate"]


class NaiveGate(BaseGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        gate_score = self.gate(inp)
        # routing weights are the softmax over the selected k (probability-
        # like, as _build_dispatch's kept-expert renormalisation expects)
        val = apply_op(
            lambda s: jax.nn.softmax(
                jax.lax.top_k(s, self.top_k)[0].astype(jnp.float32), axis=-1),
            gate_score, op_name="gate_topk_v")
        idx = apply_op(lambda s: jax.lax.top_k(s, self.top_k)[1],
                       gate_score.detach(), op_name="gate_topk_i")
        if return_all_scores:
            return val, idx, gate_score
        return val, idx
