"""GShard top-2 gate with load-balancing auxiliary loss.

Reference: moe/gate/gshard_gate.py (top-2, random second-expert dampening,
aux loss = mean(ce * me) * num_experts² as in the GShard paper)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......core.autograd import apply_op
from ......core.random import default_generator
from .naive_gate import NaiveGate

__all__ = ["GShardGate"]


class GShardGate(NaiveGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), random_routing=True,
                 group=None):
        if topk != 2:
            raise ValueError("topk should be 2 in GShardGate")
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity = capacity
        self.random_routing = random_routing

    def forward(self, x):
        gate_score = self.gate(x)
        key = default_generator.next_key() if self.random_routing else None

        def route(s):
            probs = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
            top_val, top_idx = jax.lax.top_k(probs, 2)
            # aux loss (GShard): mean(fraction-per-expert × mean-prob) × E²
            ce = jnp.mean(
                jax.nn.one_hot(top_idx[..., 0], self.tot_expert), axis=0)
            me = jnp.mean(probs, axis=0)
            aux = jnp.mean(ce * me) * (self.tot_expert ** 2)
            if key is not None:
                # randomly drop the 2nd expert when its weight is small
                # (reference: topk_val[1] < rand * topk_val[0] → mask)
                r = jax.random.uniform(key, top_val[..., 1].shape)
                keep2 = top_val[..., 1] > r * top_val[..., 0] / 2.0
                top_idx = jnp.stack(
                    [top_idx[..., 0],
                     jnp.where(keep2, top_idx[..., 1], -1)], axis=-1)
            return top_val, top_idx, aux

        # ONE recorded op: (val, idx, aux); the int idx output takes the
        # float0 cotangent path, val/aux carry gradient to the gate weights
        val, idx, aux = apply_op(route, gate_score, op_name="gshard_route")
        self.set_loss(aux)
        return val, idx
