"""Switch-Transformer top-1 gate with load-balance loss.

Reference: moe/gate/switch_gate.py (top-1 routing, aux loss from the Switch
paper: num_experts * sum(fraction_tokens * mean_prob))."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......core.autograd import apply_op
from ......core.random import default_generator
from .naive_gate import NaiveGate

__all__ = ["SwitchGate"]


class SwitchGate(NaiveGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        if topk != 1:
            raise ValueError("topk should be 1 in SwitchGate")
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, inp):
        score = self.gate(inp)
        key = default_generator.next_key() if self.training else None

        def route(s):
            if key is not None:  # training: multiplicative jitter
                noise = jax.random.uniform(
                    key, s.shape, minval=1.0 - self.switch_eps,
                    maxval=1.0 + self.switch_eps)
                s = s * noise
            probs = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
            top_val, top_idx = jax.lax.top_k(probs, 1)
            ce = jnp.mean(
                jax.nn.one_hot(top_idx[..., 0], self.tot_expert), axis=0)
            me = jnp.mean(probs, axis=0)
            aux = jnp.sum(ce * me) * self.tot_expert
            return top_val, top_idx, aux

        val, idx, aux = apply_op(route, score, op_name="switch_route")
        self.set_loss(aux)
        return val, idx
