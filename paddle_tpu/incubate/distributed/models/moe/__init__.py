from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .moe_layer import MoELayer

__all__ = ["MoELayer", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]
