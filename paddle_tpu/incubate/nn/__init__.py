"""paddle.incubate.nn parity (reference: python/paddle/incubate/nn/)."""
from . import functional
from .layer.fused_transformer import (FusedBiasDropoutResidualLayerNorm,
                                      FusedMultiTransformer)
from .memory_efficient_attention import memory_efficient_attention

__all__ = ["functional", "FusedMultiTransformer",
           "FusedBiasDropoutResidualLayerNorm", "memory_efficient_attention"]
