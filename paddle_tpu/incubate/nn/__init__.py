"""paddle.incubate.nn (fused layers land with the Pallas kernel milestone)."""
from . import functional

__all__ = ["functional"]
