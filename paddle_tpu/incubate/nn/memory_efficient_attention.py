"""Memory-efficient attention (reference:
incubate/nn/memory_efficient_attention.py → CUTLASS
fusion/cutlass/memory_efficient_attention.cu). On TPU the chunked
online-softmax path IS the memory-efficient algorithm; it routes through
ops.pallas.flash_attention (Pallas kernel on TPU, O(S) memory fallback off)."""
from __future__ import annotations

from typing import Optional

from ...core.autograd import apply_op
from ...ops.pallas import flash_attention

__all__ = ["memory_efficient_attention"]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale: Optional[float] = None,
                               training: bool = True):
    """q/k/v: [B, S, H, D]. attn_bias/p kept for API parity (bias folds in
    the XLA path only; Pallas kernel requires bias-free causal/full)."""
    if attn_bias is not None:
        from ..nn import functional  # noqa: F401  (parity: bias path below)
        import jax
        import jax.numpy as jnp

        def f(q, k, v, b):
            import math

            s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
            qt = jnp.swapaxes(q, 1, 2)
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            sc = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
            sc = sc + b.astype(jnp.float32)
            pbs = jax.nn.softmax(sc, axis=-1).astype(q.dtype)
            return jnp.swapaxes(
                jnp.einsum("bhqk,bhkd->bhqd", pbs, vt), 1, 2)

        return apply_op(f, query, key, value, attn_bias,
                        op_name="memory_efficient_attention")
    return apply_op(
        lambda q, k, v: flash_attention(q, k, v, causal=False, sm_scale=scale),
        query, key, value, op_name="memory_efficient_attention")
