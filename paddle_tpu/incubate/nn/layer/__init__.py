from .fused_transformer import (FusedBiasDropoutResidualLayerNorm,
                                FusedMultiTransformer)

__all__ = ["FusedMultiTransformer", "FusedBiasDropoutResidualLayerNorm"]
