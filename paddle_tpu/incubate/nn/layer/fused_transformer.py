"""Fused transformer layers (reference: incubate/nn/layer/fused_transformer.py
— FusedBiasDropoutResidualLayerNorm :275-area, FusedMultiTransformer :1021).

The reference's FusedMultiTransformer is a 2,000-line CUDA decoder megakernel
(fused_multi_transformer_op.cu) with in-kernel TP allreduce. TPU-native
decomposition: flash-attention (Pallas) for the context pass, decode_mha
(Pallas) over the KV cache for generation, fused LN/RMS-norm Pallas kernels
for the norm+residual glue, and mp-axis sharding annotations instead of the
in-kernel ring_id allreduce — XLA inserts the same collective after the
row-parallel projections.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ....core.autograd import apply_op
from ....core.tensor import Tensor
from ....distributed._spmd import P, set_pspec
from ....nn import functional as F
from ....nn.layer.layers import Layer
from .. import functional as incubate_F

__all__ = ["FusedBiasDropoutResidualLayerNorm", "FusedMultiTransformer"]


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference fused_transformer.py FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.embed_dim = embed_dim
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=weight_attr,
            default_initializer=None)
        from ....nn.initializer import Constant

        self.ln_scale.set_value(np.ones([embed_dim], np.float32))
        self.ln_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, is_bias=True)

    def forward(self, x, residual):
        return incubate_F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}, dropout={self._dropout_rate}"


class FusedMultiTransformer(Layer):
    """reference fused_transformer.py:1021 — a full pre-LN decoder stack with
    optional KV caches for generation.

    forward(src, attn_mask=None, caches=None, time_step=None):
    - context pass (time_step=None): causal flash attention over src
      [B, S, E]; if ``caches`` given, fills them and returns (out, caches).
    - decode pass (time_step=t): src is [B, 1, E]; reads/writes the caches
      via the decode_mha Pallas kernel.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, qkv_weight_attrs=None,
                 linear_weight_attrs=None, ffn_ln_scale_attrs=None,
                 ffn1_weight_attrs=None, ffn2_weight_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError("post-LN FusedMultiTransformer not "
                                      "supported (pre-LN is the LLM path)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self._epsilon = epsilon
        self._dropout_rate = dropout_rate
        self.activation = activation
        if num_layers <= 0:
            num_layers = (len(qkv_weight_attrs)
                          if isinstance(qkv_weight_attrs, (list, tuple))
                          else 1)
        self.num_layers = num_layers

        mk = self.create_parameter
        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            ln_s = mk([embed_dim])
            ln_s.set_value(np.ones([embed_dim], np.float32))
            ln_b = mk([embed_dim], is_bias=True)
            qkv_w = mk([3 * embed_dim, embed_dim])      # trans_qkvw layout
            qkv_b = mk([3 * embed_dim], is_bias=True)
            lin_w = mk([embed_dim, embed_dim])
            lin_b = mk([embed_dim], is_bias=True)
            f_ln_s = mk([embed_dim])
            f_ln_s.set_value(np.ones([embed_dim], np.float32))
            f_ln_b = mk([embed_dim], is_bias=True)
            ff1_w = mk([embed_dim, dim_feedforward])
            ff1_b = mk([dim_feedforward], is_bias=True)
            ff2_w = mk([dim_feedforward, embed_dim])
            ff2_b = mk([embed_dim], is_bias=True)
            # TP annotations (≙ the CUDA kernel's ring_id in-kernel allreduce:
            # column-parallel qkv/ffn1, row-parallel out-proj/ffn2)
            set_pspec(qkv_w, P("mp", None))
            set_pspec(qkv_b, P("mp"))
            # lin_w is applied TRANSPOSED (F.linear(ctx, lin_w.t())): the
            # contracted dim of the effective weight is lin_w dim 1, so
            # row-parallel shards dim 1
            set_pspec(lin_w, P(None, "mp"))
            set_pspec(ff1_w, P(None, "mp"))
            set_pspec(ff1_b, P("mp"))
            set_pspec(ff2_w, P("mp", None))
            for name_, p in [
                    ("ln_scales", ln_s), ("ln_biases", ln_b),
                    ("qkv_weights", qkv_w), ("qkv_biases", qkv_b),
                    ("linear_weights", lin_w), ("linear_biases", lin_b),
                    ("ffn_ln_scales", f_ln_s), ("ffn_ln_biases", f_ln_b),
                    ("ffn1_weights", ff1_w), ("ffn1_biases", ff1_b),
                    ("ffn2_weights", ff2_w), ("ffn2_biases", ff2_b)]:
                getattr(self, name_).append(p)
                self.add_parameter(f"{name_}_{i}", p)

    def _act(self, x):
        return F.gelu(x) if self.activation == "gelu" else F.relu(x)

    def _attn_context(self, q, k, v, attn_mask=None):
        if attn_mask is not None:
            # the provided mask is authoritative (reference
            # fused_multi_transformer_op.cu:220 applies only attn_mask —
            # callers encode causality in the mask themselves; forcing
            # causal here would break padding-only/bidirectional masks)
            return F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=False)
        from ....ops.pallas import flash_attention

        return apply_op(
            lambda qv, kv, vv: flash_attention(qv, kv, vv, causal=True),
            q, k, v, op_name="flash_attention")

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                seq_lens=None, time_step=None):
        if pre_caches is not None:
            raise NotImplementedError(
                "FusedMultiTransformer: pre_caches (prefix caches) are not "
                "supported yet — pass None")
        b, s, e = src.shape
        h, hd = self.num_heads, self.head_dim
        decode = time_step is not None
        out_caches = []
        x = src
        for i in range(self.num_layers):
            resid = x
            xn = incubate_F.fused_layer_norm(
                x, self.ln_scales[i], self.ln_biases[i], self._epsilon)
            qkv = F.linear(xn, self.qkv_weights[i].t(), self.qkv_biases[i])
            q, k, v = (t.reshape([b, s, h, hd]) for t in qkv.chunk(3, axis=-1))
            if decode:
                if attn_mask is not None:
                    raise NotImplementedError(
                        "FusedMultiTransformer decode supports ragged "
                        "batches via seq_lens (prefix masking), not "
                        "arbitrary attn_mask — pass seq_lens instead")
                k_cache, v_cache = caches[i]
                t = int(time_step)
                if seq_lens is not None:
                    # reference convention (fused_multi_transformer decode):
                    # seq_lens[i] is sequence i's CURRENT length; the cache
                    # holds its tokens compacted at [0, len). This step's
                    # k/v lands at position len (per sequence — ragged
                    # batches don't share a write offset) and attention
                    # spans the new prefix [0, len+1).
                    from ....ops._helpers import unwrap as _unwrap

                    pos = jnp.asarray(_unwrap(seq_lens), jnp.int32)

                    def upd(c, new):
                        return apply_op(
                            lambda cv, nv: cv.at[jnp.arange(b), pos].set(
                                nv[:, 0]), c, new, op_name="kv_cache_write")

                    lens = pos + 1
                else:
                    # uniform batch: write at position t, attend [0, t]
                    def upd(c, new):
                        return apply_op(
                            lambda cv, nv: cv.at[:, t].set(nv[:, 0]), c, new,
                            op_name="kv_cache_write")

                    lens = jnp.full((b,), t + 1, jnp.int32)
                k_cache = upd(k_cache, k)
                v_cache = upd(v_cache, v)
                ctx = incubate_F.masked_multihead_attention(
                    q.reshape([b, h, hd]), cache_kv=(k_cache, v_cache),
                    seq_lens=lens)
                ctx = ctx.reshape([b, 1, e])
                out_caches.append((k_cache, v_cache))
            else:
                ctx = self._attn_context(q, k, v,
                                         attn_mask=attn_mask).reshape([b, s, e])
                if caches is not None:
                    k_cache, v_cache = caches[i]
                    def fill(c, new):
                        return apply_op(
                            lambda cv, nv: cv.at[:, : nv.shape[1]].set(nv),
                            c, new, op_name="kv_cache_fill")

                    out_caches.append((fill(k_cache, k), fill(v_cache, v)))
            attn_out = F.linear(ctx, self.linear_weights[i].t(),
                                self.linear_biases[i])
            if self._dropout_rate > 0.0 and self.training:
                attn_out = F.dropout(attn_out, p=self._dropout_rate,
                                     training=True)
            # pre-LN residual stream (reference keeps the UN-normalized
            # bias_dropout_residual_out as the carried residual; LN output
            # feeds only the FFN)
            r1 = resid + attn_out
            x_ln = incubate_F.fused_layer_norm(
                r1, self.ffn_ln_scales[i], self.ffn_ln_biases[i],
                self._epsilon)
            y = F.linear(x_ln, self.ffn1_weights[i], self.ffn1_biases[i])
            y = self._act(y)
            y = F.linear(y, self.ffn2_weights[i], self.ffn2_biases[i])
            if self._dropout_rate > 0.0 and self.training:
                y = F.dropout(y, p=self._dropout_rate, training=True)
            x = r1 + y
        if caches is not None or decode:
            return x, out_caches
        return x

    @staticmethod
    def make_caches(num_layers, batch, max_seq, num_heads, head_dim,
                    dtype=jnp.float32):
        return [(Tensor(jnp.zeros((batch, max_seq, num_heads, head_dim), dtype)),
                 Tensor(jnp.zeros((batch, max_seq, num_heads, head_dim), dtype)))
                for _ in range(num_layers)]
