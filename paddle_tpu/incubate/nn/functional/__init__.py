"""incubate.nn.functional — fused-op functional surface.

Reference: python/paddle/incubate/nn/functional/ — fused_transformer.py:873
(fused_multi_transformer), fused_transformer.py:275
(fused_bias_dropout_residual_layer_norm),
fused_rotary_position_embedding.py. Backed by the Pallas kernel set
(paddle_tpu/ops/pallas_kernels.py) with eager-autograd integration.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ....core.autograd import apply_op
from ....core.tensor import Tensor
from ....ops import pallas_kernels as pk

__all__ = ["fused_rms_norm", "fused_layer_norm",
           "fused_bias_dropout_residual_layer_norm",
           "fused_rotary_position_embedding", "masked_multihead_attention",
           "fused_linear", "fused_linear_activation",
           "weight_quantize", "weight_dequantize", "weight_only_linear"]


def weight_quantize(weight, algo: str = "weight_only_int8"):
    """Per-output-channel int8/int4 weight compression for serving
    (reference analog: nn/functional/common.py:1879 quant_for_compress +
    weight_quantize op). Returns (quantized int8 weights, fp scales).

    Layout note (deviation from the reference op): weights stay in this
    framework's ``nn.Linear`` convention ``[in, out]`` UNtransposed — the
    reference returns a kernel-tiled/transposed layout bound to its CUDA
    dot; quantized checkpoints are therefore not byte-interchangeable
    across the two (dequantize + requantize to convert). int4 packs two
    nibbles per byte in the reference kernel; on TPU XLA has no
    packed-nibble dot, so int4 here quantizes to the [-7, 7] grid stored
    one value per int8 byte."""
    w = weight.value if isinstance(weight, Tensor) else jnp.asarray(weight)
    if algo not in ("weight_only_int8", "weight_only_int4"):
        raise ValueError(f"unsupported algo {algo!r}")
    qmax = 127.0 if algo.endswith("int8") else 7.0
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / qmax
    scale = jnp.maximum(scale, 1e-10)
    qw = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax,
                  qmax).astype(jnp.int8)
    return Tensor(qw), Tensor(scale.astype(jnp.float32))


def weight_dequantize(qweight, scale, algo: str = "weight_only_int8",
                      out_dtype=None):
    """Inverse of weight_quantize."""
    qw = qweight.value if isinstance(qweight, Tensor) else jnp.asarray(qweight)
    sc = scale.value if isinstance(scale, Tensor) else jnp.asarray(scale)
    out = qw.astype(jnp.float32) * sc
    if out_dtype is not None:
        out = out.astype(out_dtype)
    return Tensor(out)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None, group_size=-1):
    """x @ dequant(int8 weight) + bias (reference analog:
    _C_ops.weight_only_linear / weight_only_mat_mul,
    nn/functional/common.py:1899). The dequant multiply fuses into the
    XLA dot; weights stay int8 in HBM — the point of weight-only quant is
    the halved weight bandwidth at decode time."""
    if weight_scale is None:
        raise ValueError("weight_scale is required (from weight_quantize)")
    if group_size not in (-1, None):
        raise NotImplementedError(
            "group-wise scales are not supported; quantize with "
            "weight_quantize (per-output-channel scales, group_size=-1)")
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"unsupported weight_dtype {weight_dtype!r}")

    def f(xv, qw, sc, *b):
        w = qw.astype(xv.dtype) * sc.astype(xv.dtype)
        out = xv @ w
        if b:
            out = out + b[0]
        return out

    args = (x, weight, weight_scale) + (() if bias is None else (bias,))
    return apply_op(f, *args, op_name="weight_only_linear")


def fused_rms_norm(x, norm_weight, epsilon: float = 1e-6, **kw):
    return apply_op(lambda xv, wv: pk.rms_norm(xv, wv, eps=epsilon),
                    x, norm_weight, op_name="rms_norm")


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon: float = 1e-5,
                     residual=None, bias=None, **kw):
    def f(xv, rv, bv, gv, betav):
        return pk.fused_layer_norm(xv, rv, bv, gv, betav, eps=epsilon)

    # route only present operands through autograd
    args = {"x": x, "residual": residual, "bias": bias,
            "gamma": norm_weight, "beta": norm_bias}
    names = [k for k, v in args.items() if v is not None]

    def g(*vals):
        d = dict(zip(names, vals))
        return pk.fused_layer_norm(
            d["x"], d.get("residual"), d.get("bias"), d.get("gamma"),
            d.get("beta"), eps=epsilon)

    return apply_op(g, *[args[k] for k in names], op_name="layer_norm")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True, mode: str = "upscale_in_train", name=None):
    """reference incubate fused_transformer.py:275: out = LN(residual +
    dropout(x + bias)). Dropout composes outside the kernel — XLA fuses the
    mask multiply into the kernel's input stream."""
    y = x
    if bias is not None:
        y = y + bias
    if dropout_rate > 0.0 and training:
        from ....nn import functional as F

        y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    return fused_layer_norm(y, norm_weight=ln_scale, norm_bias=ln_bias,
                            epsilon=ln_epsilon, residual=residual)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """reference incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k/v: [B, S, H, D]; sin/cos: [S, D/2] (or [1, S, 1, D] paddle layout,
    squeezed here)."""
    def prep(cs):
        if cs is None:
            return None
        val = cs._value if isinstance(cs, Tensor) else jnp.asarray(cs)
        if val.ndim == 4:  # [1, S, 1, D] → [S, D/2] (paddle duplicates halves)
            val = val[0, :, 0, : val.shape[-1] // 2]
        return val

    cos_v, sin_v = prep(cos), prep(sin)
    if cos_v is None or sin_v is None:
        raise ValueError("cos and sin are required")

    if position_ids is not None:
        # packed/shifted sequences: gather per-token cos/sin rows →
        # [B, S, D/2]; the rotation runs as an XLA composition (the Pallas
        # kernel's block layout assumes position == sequence index)
        pid = (position_ids._value if isinstance(position_ids, Tensor)
               else jnp.asarray(position_ids))
        cos_v = jnp.take(cos_v, pid, axis=0)    # [B, S, D/2]
        sin_v = jnp.take(sin_v, pid, axis=0)

    def rot(xv):
        c, s = cos_v, sin_v
        if c.ndim == 3:                          # batched (position_ids)
            c = c[:, :, None, :]                 # [B, S, 1, D/2]
            s = s[:, :, None, :]
        else:
            c = c[None, :, None, :]              # [1, S, 1, D/2]
            s = s[None, :, None, :]
        if use_neox_rotary_style:
            d2 = xv.shape[-1] // 2
            x1, x2 = xv[..., :d2], xv[..., d2:]  # rotate-half layout
            return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)
        # GPT-J interleaved layout: pairs are (x[2i], x[2i+1])
        xp = xv.reshape(*xv.shape[:-1], xv.shape[-1] // 2, 2)
        x1, x2 = xp[..., 0], xp[..., 1]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(xv.shape)

    use_kernel = position_ids is None and use_neox_rotary_style
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        if use_kernel:
            outs.append(apply_op(lambda xv: pk.fused_rope(xv, cos_v, sin_v),
                                 t, op_name="fused_rope"))
        else:
            outs.append(apply_op(rot, t, op_name="fused_rope"))
    return tuple(outs)


def masked_multihead_attention(x, cache_kv=None, seq_lens=None, **kw):
    """Decode-time MHA over a KV cache (reference
    incubate/nn/functional/masked_multihead_attention.py →
    masked_multihead_attention_kernel). x: [B, H, D] single-step query;
    cache_kv: tuple (k_cache, v_cache) [B, S, H, D]."""
    if cache_kv is None or seq_lens is None:
        raise ValueError("cache_kv and seq_lens are required")
    k_cache, v_cache = cache_kv
    from ....core.autograd import no_grad

    # decode is inference-only (the reference CUDA kernel has no grad op);
    # the pallas kernel has no VJP, so keep it off the tape
    with no_grad():
        return apply_op(
            lambda qv, kv, vv, lv: pk.decode_mha(qv, kv, vv, lv),
            x, k_cache, v_cache, seq_lens, op_name="masked_mha")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """reference incubate fused_linear (cublasLt epilogue) — on TPU the
    bias epilogue is an XLA fusion; keep the API."""
    from ....nn import functional as F

    if transpose_weight:
        import paddle_tpu as _p

        weight = _p.t(weight)
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ....nn import functional as F
    import paddle_tpu as _p

    out = _p.matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    if bias is not None:
        out = out + bias
    if activation == "gelu":
        return F.gelu(out)
    if activation == "relu":
        return F.relu(out)
    return out
