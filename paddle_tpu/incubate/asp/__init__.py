"""ASP — automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/ (utils.py mask algorithms at
get_mask_1d:179 / get_mask_2d_greedy:313 / get_mask_2d_best:426, asp.py
ASPHelper prune_model/decorate). TPU note: the reference's end goal is
NVIDIA sparse-tensor-core kernels; on TPU the value of n:m pruning is the
model-compression semantics, so ``prune_model`` applies real masks,
``decorate`` re-applies them after every optimizer step (sparsity
invariant under training), and the MXU runs the (dense-stored) masked
weights.
"""
from __future__ import annotations

import itertools
from enum import Enum
from typing import Dict, Optional

import numpy as np

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers"]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo):
        return (CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D
                else CheckMethod.CHECK_2D)


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.py:81)."""
    a = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float(np.count_nonzero(a)) / a.size


def _reshape_1d(mat: np.ndarray, m: int):
    pad = (m - mat.shape[1] % m) % m
    padded = np.zeros((mat.shape[0], mat.shape[1] + pad), mat.dtype)
    padded[:, :mat.shape[1]] = mat
    return padded.reshape(-1, m), padded.shape


def get_mask_1d(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|.| of every m consecutive elements per row
    (reference utils.py:179)."""
    rows, shape = _reshape_1d(mat, m)
    mask = np.zeros_like(rows)
    idx = np.argsort(np.abs(rows), axis=1)[:, -n:]
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(shape)[:, :mat.shape[1]]


def check_mask_1d(mat: np.ndarray, n: int, m: int) -> bool:
    rows, _ = _reshape_1d(np.asarray(mat), m)
    return bool(np.all(np.count_nonzero(rows, axis=1) <= n))


def _reshape_2d(mat: np.ndarray, m: int):
    pad_r = (m - mat.shape[0] % m) % m
    pad_c = (m - mat.shape[1] % m) % m
    padded = np.zeros((mat.shape[0] + pad_r, mat.shape[1] + pad_c),
                      mat.dtype)
    padded[:mat.shape[0], :mat.shape[1]] = mat
    h, w = padded.shape
    blocks = padded.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3)
    return blocks.reshape(-1, m, m), padded.shape


def _unreshape_2d(blocks: np.ndarray, padded_shape, orig_shape, m: int):
    h, w = padded_shape
    out = blocks.reshape(h // m, w // m, m, m).transpose(0, 2, 1, 3)
    return out.reshape(h, w)[:orig_shape[0], :orig_shape[1]]


def get_mask_2d_greedy(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Greedy n:m along rows AND columns of each m x m block (reference
    utils.py:313)."""
    blocks, pshape = _reshape_2d(mat, m)
    masks = np.zeros_like(blocks)
    for bi, block in enumerate(np.abs(blocks)):
        order = np.argsort(block.ravel())[::-1]
        row_counts = np.zeros(m, np.int64)
        col_counts = np.zeros(m, np.int64)
        for flat in order:
            r, c = divmod(int(flat), m)
            if row_counts[r] < n and col_counts[c] < n:
                masks[bi, r, c] = 1.0
                row_counts[r] += 1
                col_counts[c] += 1
    return _unreshape_2d(masks, pshape, mat.shape, m)


_PATTERN_CACHE: Dict = {}


def _compute_valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m 0/1 patterns with exactly n per row and per column
    (reference utils.py:385)."""
    key = (n, m)
    if key in _PATTERN_CACHE:
        return _PATTERN_CACHE[key]
    row_patterns = [p for p in itertools.product([0, 1], repeat=m)
                    if sum(p) == n]
    valid = []
    for combo in itertools.product(row_patterns, repeat=m):
        arr = np.asarray(combo)
        if np.all(arr.sum(axis=0) == n):
            valid.append(arr)
    pats = np.asarray(valid, np.float64)
    _PATTERN_CACHE[key] = pats
    return pats


def get_mask_2d_best(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Exhaustive best n:m 2-D pattern per block (reference utils.py:426)."""
    blocks, pshape = _reshape_2d(mat, m)
    pats = _compute_valid_2d_patterns(n, m)          # [P, m, m]
    scores = np.einsum("bij,pij->bp", np.abs(blocks), pats)
    best = pats[np.argmax(scores, axis=1)]
    return _unreshape_2d(best.astype(mat.dtype), pshape, mat.shape, m)


def check_mask_2d(mat: np.ndarray, n: int, m: int) -> bool:
    blocks, _ = _reshape_2d(np.asarray(mat), m)
    nz = blocks != 0
    return bool(np.all(nz.sum(axis=1) <= n) and np.all(nz.sum(axis=2) <= n))


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n: int = 2, m: int = 4):
    """Mask for a (possibly >2-D) tensor (reference utils.py:480): shaped
    over the last two dims, others folded into rows."""
    a = np.asarray(tensor.numpy() if hasattr(tensor, "numpy") else tensor)
    dtype = a.dtype
    shape = a.shape
    if a.ndim == 1:
        mat = a.reshape(1, -1)
    elif a.ndim == 2:
        mat = a
    else:
        mat = a.reshape(-1, shape[-1])
    fn = globals()[func_name.value if isinstance(func_name, MaskAlgo)
                   else func_name]
    mask = fn(mat.astype(np.float64), n, m)
    return mask.reshape(shape).astype(dtype)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n: int = 2,
                   m: int = 4) -> bool:
    a = np.asarray(tensor.numpy() if hasattr(tensor, "numpy") else tensor)
    mat = a.reshape(-1, a.shape[-1]) if a.ndim != 2 else a
    fn = globals()[func_name.value if isinstance(func_name, CheckMethod)
                   else func_name]
    return fn(mat, n, m)


# ---------------------------------------------------------------------------
# ASPHelper — model-level pruning + optimizer decoration (asp.py parity)
# ---------------------------------------------------------------------------

# mask/exclusion state lives ON the model object (attributes below) — an
# id()-keyed registry would leak masks for the process lifetime and could
# hand a recycled id the previous model's masks
_MASK_ATTR = "_asp_masks"
_EXCL_ATTR = "_asp_excluded"


def _supported(name: str, param) -> bool:
    # reference supported_layer_list: fc/linear/conv weights; biases and
    # norms are never pruned
    v = param.value if hasattr(param, "value") else param
    if getattr(v, "ndim", 0) < 2:
        return False
    return "weight" in name.split(".")[-1]


def set_excluded_layers(model, param_names):
    """Exclude sublayer/param names from pruning (reference asp.py:121)."""
    excl = getattr(model, _EXCL_ATTR, None)
    if excl is None:
        excl = set()
        object.__setattr__(model, _EXCL_ATTR, excl)
    excl.update(param_names)


def reset_excluded_layers(model=None):
    if model is not None and hasattr(model, _EXCL_ATTR):
        getattr(model, _EXCL_ATTR).clear()


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every supported weight (reference asp.py:204).
    Returns {param_name: mask}."""
    import jax.numpy as jnp

    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    excluded = getattr(model, _EXCL_ATTR, set())
    masks = {}
    device_masks = {}
    for name, p in model.named_parameters():
        if not _supported(name, p) or any(e in name for e in excluded):
            continue
        mask = create_mask(p, func_name=algo, n=n, m=m)
        # masks stay resident on device: the per-step re-masking in
        # decorate() must be value * mask with no host round-trip
        mask_dev = jnp.asarray(mask, p.value.dtype)
        p.set_value(p.value * mask_dev)
        masks[name] = mask
        device_masks[name] = mask_dev
    if with_mask:
        object.__setattr__(model, _MASK_ATTR, device_masks)
    return masks


from ...distributed.fleet.meta_optimizers.base import MetaOptimizerWrapper


class OptimizerWithSparsityGuarantee(MetaOptimizerWrapper):
    """Re-applies the pruning masks after every step so training keeps the
    n:m structure (reference asp.py ASPHelper._decorate). Shares the
    wrapper delegation shell (minimize→self.step, state_dict forwarding)
    with the fleet meta-optimizers."""

    def __init__(self, optimizer, model):
        super().__init__(optimizer)
        self._model = model

    def step(self):
        self._inner_opt.step()
        masks = getattr(self._model, _MASK_ATTR, {})
        if not masks:
            return
        for name, p in self._model.named_parameters():
            mask = masks.get(name)
            if mask is not None:
                # device-resident multiply; no host sync per step
                p.set_value(p.value * mask)


def decorate(optimizer, model=None):
    """Wrap the optimizer with the sparsity guarantee (reference
    asp.py:160). ``model`` binds the mask set (the eager API needs it
    explicitly — there is no global program to look it up from)."""
    if model is None:
        raise ValueError(
            "decorate() needs the model the masks were created for: "
            "asp.decorate(optimizer, model)")
    return OptimizerWithSparsityGuarantee(optimizer, model)
