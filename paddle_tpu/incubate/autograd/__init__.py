"""paddle.incubate.autograd — functional/primitive AD surface (reference:
python/paddle/incubate/autograd/__init__.py — Jacobian/Hessian/jvp/vjp
from functional.py, forward_grad/grad from primapi.py, prim2orig from
primx.py, enable_prim/disable_prim/prim_enabled from utils.py).

TPU-native: the reference's "prim" mode lowers composite ops to primitive
ops so a rule-based transpose can differentiate them — that IS JAX's
execution model (every op is a primitive with jvp/transpose rules), so
the toggles are honest no-ops and the functional surface maps straight
onto jax.jvp/vjp/jacobian. Values round-trip as framework Tensors.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["Jacobian", "Hessian", "jvp", "vjp", "forward_grad", "grad",
           "prim2orig", "enable_prim", "disable_prim", "prim_enabled"]


def _raw(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _raw_tree(xs):
    if isinstance(xs, (list, tuple)):
        return [_raw(x) for x in xs]
    return _raw(xs)


def _wrap_tree(vals):
    if isinstance(vals, (list, tuple)):
        return [Tensor(v) for v in vals]
    return Tensor(vals)


def _pure(func: Callable):
    def f(*raws):
        out = func(*[Tensor(r) for r in raws])
        # outputs may be a Tensor or a (possibly nested) sequence of them
        return jax.tree.map(
            lambda o: o._value if isinstance(o, Tensor) else o, out,
            is_leaf=lambda o: isinstance(o, Tensor))

    return f


class Jacobian:
    """Lazy Jacobian (reference functional.Jacobian): J[i, j] indexes the
    full matrix; ``batch_axis=0`` treats dim 0 as batch. Computed once via
    jax.jacrev on first access."""

    def __init__(self, func, xs, is_batched: bool = False,
                 batch_axis=None):
        if batch_axis not in (None, 0):
            raise ValueError(
                f"batch_axis must be None or 0, got {batch_axis!r}")
        self._func = func
        self._xs = xs
        self._batched = is_batched or batch_axis == 0
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        xs = _raw_tree(self._xs)
        multi = isinstance(xs, list)
        f = _pure(self._func)

        if self._batched:
            def single(*row):
                return f(*[r[None] for r in row])[0]

            jac = jax.vmap(jax.jacrev(single, argnums=tuple(
                range(len(xs))) if multi else 0))(*(xs if multi else [xs]))
        else:
            jac = jax.jacrev(f, argnums=tuple(range(len(xs)))
                             if multi else 0)(*(xs if multi else [xs]))
        if multi:
            # concatenate along the input dimension (reference lays the
            # multi-input Jacobian out as one wide matrix). Each jacrev
            # block has shape (*out_shape, *in_shape_i): reshape to
            # (out_size, in_size_i) from the KNOWN output size so scalar
            # inputs and multi-dim outputs keep the right layout.
            import math

            if self._batched:
                out_aval = jax.eval_shape(
                    lambda *a: f(*a), *[a[:1] for a in xs])
                out_size = math.prod(out_aval.shape[1:]) or 1
                flat = [j.reshape(j.shape[0], out_size, -1) for j in jac]
            else:
                out_aval = jax.eval_shape(f, *xs)
                out_size = math.prod(out_aval.shape) or 1
                flat = [j.reshape(out_size, -1) for j in jac]
            jac = jnp.concatenate(flat, axis=-1)
        self._mat = jac
        return jac

    def __getitem__(self, idx):
        return Tensor(jnp.asarray(self._compute())[idx])

    @property
    def shape(self):
        return tuple(jnp.shape(self._compute()))

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


class Hessian(Jacobian):
    """Lazy Hessian of a SCALAR-output func (reference functional.Hessian)."""

    def _compute(self):
        if self._mat is not None:
            return self._mat
        xs = _raw_tree(self._xs)
        multi = isinstance(xs, list)
        args = xs if multi else [xs]
        f = _pure(self._func)
        # flatten-concat ALL inputs into one vector so the Hessian is the
        # full (n, n) matrix INCLUDING cross terms (argnums=0 alone would
        # silently drop d2f/dxdy for multi-input funcs)
        import math

        shapes = [jnp.shape(a) for a in args]
        out_aval = jax.eval_shape(f, *args)
        out_sz = math.prod(getattr(out_aval, "shape", ())) or 1
        per_item = math.prod(getattr(out_aval, "shape", ())[1:]) or 1
        if (per_item if self._batched else out_sz) != 1:
            raise TypeError(
                f"Hessian needs a scalar-output function (per batch item "
                f"when batched); got output shape {out_aval.shape}")
        if self._batched:
            row_shapes = [s[1:] for s in shapes]
            row_sizes = [math.prod(s) if s else 1 for s in row_shapes]
            offs = [0]
            for s in row_sizes:
                offs.append(offs[-1] + s)

            def single(z):
                parts = [z[offs[i]:offs[i + 1]].reshape(row_shapes[i])
                         for i in range(len(args))]
                return jnp.sum(f(*[p[None] for p in parts]))

            zb = jnp.concatenate(
                [a.reshape(a.shape[0], -1) for a in args], axis=-1)
            h = jax.vmap(jax.hessian(single))(zb)
        else:
            sizes = [int(jnp.size(a)) for a in args]
            offs = [0]
            for s in sizes:
                offs.append(offs[-1] + s)

            def scalar_of_vec(z):
                parts = [z[offs[i]:offs[i + 1]].reshape(shapes[i])
                         for i in range(len(args))]
                return jnp.sum(f(*parts))

            z = jnp.concatenate([a.ravel() for a in args])
            h = jax.hessian(scalar_of_vec)(z)
        self._mat = h
        return h


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v) (reference functional.jvp;
    v defaults to ones)."""
    raws = _raw_tree(xs)
    multi = isinstance(raws, list)
    args = raws if multi else [raws]
    tangents = (_raw_tree(v) if v is not None
                else [jnp.ones_like(a) for a in args])
    if not isinstance(tangents, list):
        tangents = [tangents]
    f = _pure(func)
    out, tangent_out = jax.jvp(f, tuple(args), tuple(tangents))
    return _wrap_tree(out), _wrap_tree(tangent_out)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns (func(xs), J^T @ v) (reference
    functional.vjp; v defaults to ones over the output)."""
    raws = _raw_tree(xs)
    multi = isinstance(raws, list)
    args = raws if multi else [raws]
    f = _pure(func)
    out, pullback = jax.vjp(f, *args)
    if v is not None:
        cot = jax.tree.map(
            lambda o: o._value if isinstance(o, Tensor) else jnp.asarray(o),
            v, is_leaf=lambda o: isinstance(o, Tensor))
        if isinstance(cot, list):      # match jax's tuple output structure
            cot = tuple(cot)
    else:
        cot = jax.tree.map(jnp.ones_like, out)
    grads = pullback(cot)
    grads = list(grads) if multi else grads[0]
    return _wrap_tree(out), _wrap_tree(grads)


def forward_grad(outputs, inputs, grad_inputs=None):
    """reference primapi.forward_grad — forward-mode gradients in the
    static prim world. Recorded-program/static use should go through
    Executor + input_grad fetches; eager use maps to :func:`jvp`."""
    raise NotImplementedError(
        "forward_grad operates on the reference's static prim program; "
        "use incubate.autograd.jvp (eager forward-mode) or "
        "static append_backward + Executor fetches instead")


def grad(outputs, inputs, grad_outputs=None):
    """reference primapi.grad (static prim reverse-mode). Eager
    equivalent: paddle.grad — delegated for API familiarity."""
    from ...autograd.functional import grad as eager_grad

    return eager_grad(outputs, inputs, grad_outputs)


def prim2orig(block=None):
    """No-op on TPU: there is no separate prim dialect to lower back —
    JAX programs are already primitive-level (reference primx.prim2orig)."""
    return None


_prim_flag = [False]


def enable_prim():
    """No-op toggle kept for parity: JAX *is* the primitive autodiff
    backend (every op has jvp/transpose rules); there is no composite
    mode to switch away from."""
    _prim_flag[0] = True


def disable_prim():
    _prim_flag[0] = False


def prim_enabled() -> bool:
    return _prim_flag[0]
