"""paddle.incubate top-level functions (reference: python/paddle/incubate/
__init__.py — segment ops, graph ops, fused softmax-mask, identity_loss,
LookAhead/ModelAverage optimizer wrappers).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..geometric import (reindex_graph as graph_reindex,
                         sample_neighbors as graph_sample_neighbors,
                         segment_max, segment_mean, segment_min, segment_sum,
                         send_u_recv as graph_send_recv)

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "graph_send_recv", "graph_reindex", "graph_sample_neighbors",
           "graph_khop_sampler", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "identity_loss",
           "LookAhead", "ModelAverage"]


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference incubate/operators/
    graph_khop_sampler.py): chains per-hop sample_neighbors and reindexes
    the union. Host-side like the per-hop sampler (data pipeline work)."""
    from ..geometric import sample_neighbors
    from ..ops._helpers import unwrap

    all_src, all_dst = [], []
    frontier = input_nodes
    for k in sample_sizes:
        neigh, counts = sample_neighbors(row, colptr, frontier,
                                         sample_size=int(k))
        cnp = np.asarray(unwrap(counts))
        fnp = np.asarray(unwrap(frontier))
        all_src.append(np.asarray(unwrap(neigh)))
        all_dst.append(np.repeat(fnp, cnp))
        frontier = Tensor(jnp.asarray(np.unique(np.asarray(unwrap(neigh)))))
    src = np.concatenate(all_src) if all_src else np.zeros((0,), np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros((0,), np.int64)
    # compact ids: input nodes first (reference out_nodes ordering), then
    # new nodes in order of first appearance in the sampled edges
    inp = np.asarray(unwrap(input_nodes)).ravel()
    mapping = {int(n): i for i, n in enumerate(inp)}
    out_nodes = list(inp)
    for n in np.concatenate([src, dst]):
        n = int(n)
        if n not in mapping:
            mapping[n] = len(out_nodes)
            out_nodes.append(n)
    r_src = np.asarray([mapping[int(n)] for n in src], np.int64)
    r_dst = np.asarray([mapping[int(n)] for n in dst], np.int64)
    return (Tensor(jnp.asarray(r_src)), Tensor(jnp.asarray(r_dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int64))))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) (reference incubate/operators/softmax_mask_fuse.py
    — the CUDA fusion exists to avoid materializing x+mask; XLA fuses the
    add into the softmax on its own)."""
    return apply_op(
        lambda v, m: jnp.asarray(
            jnp.exp(v + m - jnp.max(v + m, -1, keepdims=True))
            / jnp.sum(jnp.exp(v + m - jnp.max(v + m, -1, keepdims=True)),
                      -1, keepdims=True)),
        x, mask, op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper-triangle (future) positions masked
    (reference softmax_mask_fuse_upper_triangle — causal attention
    softmax)."""

    def f(v):
        s = v.shape[-1]
        mask = jnp.tril(jnp.ones((v.shape[-2], s), bool))
        z = jnp.where(mask, v, -1e30)
        z = z - jnp.max(z, -1, keepdims=True)
        e = jnp.exp(z) * mask
        return e / jnp.maximum(jnp.sum(e, -1, keepdims=True), 1e-30)

    return apply_op(f, x, op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss (reference incubate identity_loss — IPU
    pipeline marker). Applies the requested reduction."""
    red = {"none": 0, "sum": 1, "mean": 2}.get(reduction, reduction)
    if red == 0:
        return apply_op(lambda v: v, x, op_name="identity_loss")
    if red == 1:
        return apply_op(lambda v: jnp.sum(v), x, op_name="identity_loss")
    return apply_op(lambda v: jnp.mean(v), x, op_name="identity_loss")


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate/optimizer/lookahead
    .py; Zhang et al. 2019): every k steps, slow weights interpolate
    toward fast weights and fast weights reset to slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self._inner_opt = inner_optimizer
        self.alpha = alpha
        self.k = max(1, int(k))
        self._count = 0
        self._slow = {}

    def step(self):
        self._inner_opt.step()
        self._count += 1
        params = self._inner_opt._parameter_list or []
        if self._count == 1:
            for p in params:
                self._slow[id(p)] = p.value
        if self._count % self.k:
            return
        for p in params:
            slow = self._slow.get(id(p), p.value)
            slow = slow + self.alpha * (p.value - slow)
            self._slow[id(p)] = slow
            p.set_value(slow)

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class ModelAverage:
    """Running average of parameters for evaluation (reference incubate/
    optimizer/modelaverage.py): accumulates sums, apply()/restore() swap
    the averaged weights in and out."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = {id(p): jnp.zeros_like(p.value) for p in self._params}
        self._n = 0
        self._backup = None

    def step(self):
        self._n += 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p.value
        if self._n > self._max_w:
            # restart window (reference resets accumulators past max)
            for p in self._params:
                self._sum[id(p)] = p.value.astype(self._sum[id(p)].dtype)
            self._n = 1

    class _Guard:
        def __init__(self, outer, need_restore):
            self.outer = outer
            self.need_restore = need_restore

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            if self.need_restore:
                self.outer.restore()
            return False

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p.value for p in self._params}
        n = max(self._n, 1)
        for p in self._params:
            p.set_value((self._sum[id(p)] / n).astype(p.value.dtype))
        return self._Guard(self, need_restore)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p.set_value(self._backup[id(p)])
        self._backup = None

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        return None, None
