"""paddle.distributed.spawn parity (reference: distributed/spawn.py) —
multiprocess helper for CPU-simulation of multi-process training. On TPU
proper, one process owns all chips; spawn exists for the reference's
process-per-worker tests."""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Optional, Tuple

__all__ = ["spawn"]


def _worker(func, rank, nprocs, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(rank, *args) if _takes_rank(func) else func(*args)


def _takes_rank(func) -> bool:
    import inspect

    try:
        params = inspect.signature(func).parameters
        return len(params) >= 1 and next(iter(params)) in ("rank", "local_rank")
    except (TypeError, ValueError):
        return False


def spawn(func, args: Tuple = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    ctx = mp.get_context(options.get("start_method", "spawn"))
    procs = []
    env = {k: v for k, v in os.environ.items()}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, args, env), daemon=daemon)
        p.start()
        procs.append(p)

    class Context:
        processes = procs

        def join(self, timeout: Optional[float] = None):
            for p in procs:
                p.join(timeout)
            codes = [p.exitcode for p in procs]
            if any(c not in (0, None) for c in codes):
                raise RuntimeError(f"spawned process failed: exit codes {codes}")
            return all(c == 0 for c in codes)

    c = Context()
    if join:
        c.join()
    return c
