"""Parallel environment + DataParallel (distributed/parallel.py:190,917 parity).

init_parallel_env ≙ reference's TCPStore+ProcessGroupNCCL bring-up
(parallel.py:1056-1101): on TPU this is ``jax.distributed.initialize`` (the
JAX coordinator plays TCPStore's role) plus building the global mesh.

DataParallel ≙ reference DataParallel+EagerReducer (collective/reducer.cc):
TPU-native form — the model's train step is compiled with batch sharded over
the ``dp`` axis; gradient allreduce is inserted by XLA from the sharding
(GSPMD), or taken explicitly via grad hooks in the eager path.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..core.tensor import Tensor
from .communication import all_reduce
from .communication.core import ReduceOp
from .env import get_rank, get_world_size
from .topology import build_mesh, get_mesh, set_mesh

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "DataParallel",
           "ParallelEnv"]

_initialized = [False]
_global_store = [None]


def _create_store():
    """Out-of-band rendezvous store (reference parallel.py:1077 creates
    core.TCPStore from MASTER_ADDR/PORT before group bring-up). Backed by
    the native C++ TCPStore; returns None when no master env is set or the
    native lib is unavailable."""
    master = os.environ.get("MASTER_ADDR")
    port = os.environ.get("MASTER_PORT")
    if not master or not port:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    try:
        from ..native import TCPStore

        return TCPStore(master, int(port), is_master=(rank == 0),
                        world_size=world)
    except (RuntimeError, OSError, ConnectionError):
        return None


def get_store():
    return _global_store[0]


def init_parallel_env(mesh=None, **mesh_degrees):
    """Bring up the distributed runtime and the global mesh.

    Multi-host: PADDLE_TRAINER_ENDPOINTS/PADDLE_TRAINER_ID (reference env
    contract, launch/controllers/collective.py) map to the JAX coordinator.
    """
    if _initialized[0]:
        return ParallelEnv()
    _global_store[0] = _create_store()
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
    nnodes = int(os.environ.get("PADDLE_NNODES", "1"))
    if endpoints and nnodes > 1:
        coord = endpoints.split(",")[0]
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nnodes,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", 0)),
        )
    if mesh is not None:
        set_mesh(mesh)
    elif mesh_degrees:
        set_mesh(build_mesh(**mesh_degrees))
    else:
        set_mesh(build_mesh())  # pure-dp default over all devices
    _initialized[0] = True
    return ParallelEnv()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    local_rank = rank


class DataParallel:
    """paddle.DataParallel parity (distributed/parallel.py:190).

    Wraps a Layer; after ``loss.backward()`` call ``apply_collective_grads``
    (or rely on the hybrid optimizer) to average grads over dp. In the
    compiled path (to_static / fleet train steps), dp-sharded batches make
    XLA insert the grad psum automatically, so this wrapper is a passthrough
    there — matching the reference where DataParallel is a no-op under
    sharding-parallel modes (fleet/model.py:149).
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    __call__ = forward

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    # -- reference API surface --------------------------------------------
    def no_sync(self):
        import contextlib

        parent = self

        @contextlib.contextmanager
        def ctx():
            prev = parent._grad_sync_enabled
            parent._grad_sync_enabled = False
            try:
                yield
            finally:
                parent._grad_sync_enabled = prev

        return ctx()

    def apply_collective_grads(self):
        """Average grads across dp (≙ EagerReducer fused allreduce,
        reducer.cc:938). Grads here are global arrays in single-controller
        SPMD — when the forward was computed with a dp-sharded batch the
        grad is already the full-batch gradient, so this is the explicit
        eager path for per-shard gradients following the stacked convention."""
        if not self._grad_sync_enabled:
            return
        from .topology import axis_size

        n = axis_size("dp")
        if n <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None and p.grad.shape and p.grad.shape[0] == n:
                all_reduce(p.grad, op=ReduceOp.AVG,
                           group=self._group or _dp_group())

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    @property
    def parameters(self):
        return self._layers.parameters

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


def _dp_group():
    from .topology import Group

    return Group("dp", get_mesh())
