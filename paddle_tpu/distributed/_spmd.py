"""SPMD sharding helpers — the glue between Layer parameters and GSPMD.

TPU-native replacement for the reference's per-process parameter splitting
(fleet/layers/mpu/mp_layers.py slices each rank's shard at construction
time). Here a parameter always holds the FULL logical array and carries a
``PartitionSpec``; under ``jax.jit`` over the global mesh, GSPMD places the
shards and inserts the collectives (the scaling-book recipe: pick a mesh,
annotate shardings, let XLA do the rest). Eagerly (no jit) the full array is
used directly, so single-device math is bit-identical to the parallel run —
which is exactly the reference's numerical-parity test contract
(SURVEY.md §4.2).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .topology import get_mesh

__all__ = ["P", "set_pspec", "get_pspec", "constraint", "layer_pspecs",
           "named_sharding", "shard_params"]


def set_pspec(param, spec) -> None:
    """Attach a PartitionSpec to a parameter/tensor (metadata only)."""
    try:
        param.pspec = spec
    except AttributeError:
        object.__setattr__(param, "pspec", spec)
    # reference-parity flags (mp_layers sets is_distributed/split_axis)
    try:
        axes = [i for i, a in enumerate(spec) if a is not None]
        param.is_distributed = bool(axes)
        param.split_axis = axes[0] if axes else None
    except (AttributeError, TypeError):
        pass


def get_pspec(param) -> Optional[P]:
    return getattr(param, "pspec", None)


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop axes absent from (or size-1 in) the mesh so specs written for the
    full hybrid axis set stay valid on smaller meshes."""
    def keep(a):
        if a is None:
            return None
        names = a if isinstance(a, (tuple, list)) else (a,)
        live = tuple(n for n in names if n in mesh.shape and mesh.shape[n] > 1)
        if not live:
            return None
        return live if len(live) > 1 else live[0]

    return P(*(keep(a) for a in spec))


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def constraint(x, spec, mesh: Optional[Mesh] = None):
    """``with_sharding_constraint`` that is a no-op outside tracing and on
    axes the current mesh doesn't have. Accepts Tensor or jax array; returns
    the same kind."""
    from ..core.tensor import Tensor

    val = x._value if isinstance(x, Tensor) else x
    if not _is_tracer(val):
        return x
    mesh = mesh or get_mesh()
    fspec = _filter_spec(spec, mesh)
    if all(a is None for a in fspec):
        return x
    out = jax.lax.with_sharding_constraint(val, NamedSharding(mesh, fspec))
    if isinstance(x, Tensor):
        t = Tensor(out, stop_gradient=x.stop_gradient)
        t._node = getattr(x, "_node", None)
        return t
    return out


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, _filter_spec(spec, mesh))


def layer_pspecs(layer) -> Dict[str, P]:
    """name → PartitionSpec for every parameter/buffer of a Layer (replicated
    P() when unannotated). Matches Layer.raw_state keys, so the dict drops
    straight into jit in_shardings."""
    specs = {}
    for name, p in layer.named_parameters():
        specs[name] = get_pspec(p) or P()
    for name, b in layer.named_buffers():
        specs[name] = get_pspec(b) or P()
    return specs


def shard_params(layer, mesh: Optional[Mesh] = None):
    """Physically place every parameter of `layer` onto the mesh according to
    its pspec (device_put with NamedSharding). The eager analog of jit
    in_shardings — call once after building a model on a live mesh."""
    mesh = mesh or get_mesh()
    for _, p in list(layer.named_parameters()) + list(layer.named_buffers()):
        spec = get_pspec(p) or P()
        sh = NamedSharding(mesh, _filter_spec(spec, mesh))
        p._inplace_(jax.device_put(p._value, sh))
    return layer
