"""Sequence/context parallelism — ring attention + Ulysses (all-to-all).

The reference snapshot has NO sequence parallelism (SURVEY.md §5.7: no ring
attention, no Ulysses, no context-parallel utilities); long sequences lean
on FlashAttention + recompute. This module designs SP fresh as a first-class
mesh axis ``sp``, the capability extension the TPU build requires:

- **ring attention**: Q stays put; K/V blocks rotate around the sp ring via
  ``ppermute`` while each device accumulates its queries' attention with an
  online softmax (flash-attention recurrence across devices). The LOCAL
  block is itself chunked (``block_q`` x ``block_k`` inner scans), so peak
  per-device score memory is O(block_q · block_k) — NOT O((S/R)²) — and
  million-token contexts fit (tests/test_long_context.py proves 256k/1M
  compile-only). Causal runs skip ring steps that are entirely in the
  future (their sources hold only later positions), saving ~half the
  FLOPs. Causality is enforced with global position masks, so results are
  bit-comparable to single-device attention.
- **Ulysses**: all-to-all swaps the sharded axis seq↔heads, runs ordinary
  (flash) attention with full sequence per head group, and swaps back.
  Cheaper than ring for moderate S (two all-to-alls), requires H % sp == 0.

Both are pure jax functions over GLOBAL arrays in paddle layout
[B, S, H, D] — under jit on an sp mesh the arrays are sharded on S (ring) or
re-sharded via all-to-all (Ulysses); eagerly (1 device) they reduce to exact
attention, which is the parity test contract.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .topology import get_mesh

__all__ = ["ring_attention", "ulysses_attention", "split_sequence",
           "gather_sequence", "RingFlashAttention"]


def _online_block(q, k, v, acc, m, l, qpos, kpos, causal, scale):
    """One flash-attention block accumulation step (fp32 state)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = s.astype(jnp.float32)
    if causal:
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    alpha = jnp.where(jnp.isfinite(alpha), alpha, 0.0)
    l = l * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return acc, m_new, l


def _pick_chunk(s: int, target: int) -> int:
    """Largest divisor of s that is <= target (chunked scans need exact
    tiling; sequences here are powers of two in practice)."""
    for c in (target, 1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if c <= target and s % c == 0:
            return c
    return 1


def _local_attend(q, kb, vb, state, q0, k0, causal, scale, bq, bk):
    """Chunked local attention of q against one K/V block, merged into the
    running online-softmax ``state`` = (acc, m, l). Scores exist only at
    (bq, bk) granularity — the long-context contract. ``q0``/``k0`` are
    the GLOBAL positions of the block starts."""
    b, sq, h, d = q.shape
    sk = kb.shape[1]
    nq, nk = sq // bq, sk // bk

    def q_step(state, qi):
        acc, m, l = state
        qs = qi * bq
        qc = lax.dynamic_slice_in_dim(q, qs, bq, axis=1)
        a = lax.dynamic_slice_in_dim(acc, qs, bq, axis=2)
        mm = lax.dynamic_slice_in_dim(m, qs, bq, axis=2)
        ll = lax.dynamic_slice_in_dim(l, qs, bq, axis=2)
        qpos = q0 + qs + jnp.arange(bq)

        def k_step(carry, ki):
            a, mm, ll = carry
            ks = ki * bk
            kc = lax.dynamic_slice_in_dim(kb, ks, bk, axis=1)
            vc = lax.dynamic_slice_in_dim(vb, ks, bk, axis=1)
            kpos = k0 + ks + jnp.arange(bk)
            a, mm, ll = _online_block(qc, kc, vc, a, mm, ll, qpos, kpos,
                                      causal, scale)
            return (a, mm, ll), None

        (a, mm, ll), _ = lax.scan(k_step, (a, mm, ll), jnp.arange(nk))
        acc = lax.dynamic_update_slice_in_dim(acc, a, qs, axis=2)
        m = lax.dynamic_update_slice_in_dim(m, mm, qs, axis=2)
        l = lax.dynamic_update_slice_in_dim(l, ll, qs, axis=2)
        return (acc, m, l), None

    state, _ = lax.scan(q_step, state, jnp.arange(nq))
    return state


def ring_attention(q, k, v, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   mesh: Optional[Mesh] = None, axis: str = "sp",
                   block_q: int = 1024, block_k: int = 1024):
    """Ring attention over the ``axis`` mesh dim. q/k/v: [B, S, H, D]
    global. Use under jit with S sharded over ``axis``; on a 1-wide axis
    it computes plain (chunked) exact attention.

    Score memory is O(block_q · block_k) per device regardless of S —
    the local block runs the same online-softmax recurrence chunked — so
    context length is bounded by the O(S/R · D) q/k/v + accumulator
    footprint, not by an (S/R)² buffer. Causal runs skip ring steps whose
    source block lies entirely in the future.
    """
    mesh = mesh or get_mesh()
    R = int(mesh.shape.get(axis, 1))
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if R == 1:
        b, s_, h, _ = q.shape
        bq, bk = _pick_chunk(s_, block_q), _pick_chunk(s_, block_k)
        acc = jnp.zeros((b, h, s_, d), jnp.float32)
        m = jnp.full((b, h, s_), -jnp.inf, jnp.float32)
        l = jnp.zeros((b, h, s_), jnp.float32)
        acc, m, l = _local_attend(q, k, v, (acc, m, l), 0, 0, causal,
                                  scale, bq, bk)
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)

    def worker(q, k, v):
        r = lax.axis_index(axis)
        b, sq, h, _ = q.shape  # local seq block
        bq, bk = _pick_chunk(sq, block_q), _pick_chunk(sq, block_k)
        perm = [(i, (i + 1) % R) for i in range(R)]  # rotate kv around ring

        acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
        m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq), jnp.float32)

        def step(carry, i):
            acc, m, l, kb, vb = carry
            # block i holds rank (r - i) mod R's kv
            src = (r - i) % R

            def compute(state):
                return _local_attend(q, kb, vb, state, r * sq, src * sq,
                                     causal, scale, bq, bk)

            if causal:
                # a source strictly in the future contributes nothing:
                # skip its whole chunked sweep (~half the ring FLOPs)
                acc, m, l = lax.cond(src <= r, compute,
                                     lambda st: st, (acc, m, l))
            else:
                acc, m, l = compute((acc, m, l))
            kb = lax.ppermute(kb, axis, perm)
            vb = lax.ppermute(vb, axis, perm)
            return (acc, m, l, kb, vb), None

        (acc, m, l, _, _), _ = lax.scan(
            step, (acc0, m0, l0, k, v), jnp.arange(R))
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)

    from jax import shard_map

    spec = P(None, axis, None, None)
    return shard_map(worker, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names={axis},
                     check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      mesh: Optional[Mesh] = None, axis: str = "sp",
                      attn_fn=None):
    """Ulysses (DeepSpeed) SP: all-to-all seq→heads, full-seq attention on
    H/R heads, all-to-all back. q/k/v: [B, S, H, D] global."""
    mesh = mesh or get_mesh()
    R = int(mesh.shape.get(axis, 1))
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    def full_attn(q, k, v):
        b, s_, h, _ = q.shape
        pos = jnp.arange(s_)
        acc = jnp.zeros((b, h, s_, d), jnp.float32)
        m = jnp.full((b, h, s_), -jnp.inf, jnp.float32)
        l = jnp.zeros((b, h, s_), jnp.float32)
        acc, m, l = _online_block(q, k, v, acc, m, l, pos, pos, causal, scale)
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)

    if attn_fn is None:
        attn_fn = full_attn
    if R == 1:
        return attn_fn(q, k, v)
    if q.shape[2] % R != 0:
        raise ValueError(
            f"ulysses needs num_heads {q.shape[2]} divisible by sp={R}")

    def worker(q, k, v):
        # local: [B, S/R, H, D] → all_to_all → [B, S, H/R, D]
        def a2a_fwd(x):
            x = lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                               tiled=True)
            return x

        def a2a_bwd(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        out = attn_fn(a2a_fwd(q), a2a_fwd(k), a2a_fwd(v))
        return a2a_bwd(out)

    from jax import shard_map

    spec = P(None, axis, None, None)
    return shard_map(worker, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names={axis},
                     check_vma=False)(q, k, v)


def split_sequence(x, mesh: Optional[Mesh] = None, axis: str = "sp",
                   seq_dim: int = 1):
    """Annotate x as sequence-sharded (GSPMD scatters on first use)."""
    from ._spmd import constraint

    nd = x.ndim
    spec = [None] * nd
    spec[seq_dim] = axis
    return constraint(x, P(*spec), mesh)


def gather_sequence(x, mesh: Optional[Mesh] = None, axis: str = "sp",
                    seq_dim: int = 1):
    """Annotate x replicated on the sp axis (all-gather on use)."""
    from ._spmd import constraint

    return constraint(x, P(*([None] * x.ndim)), mesh)


class RingFlashAttention:
    """Layer-ish callable holding (causal, scale, axis) config; drops into
    transformer blocks where a flash_attention callable is expected."""

    def __init__(self, causal: bool = True, sm_scale=None, axis: str = "sp"):
        self.causal = causal
        self.sm_scale = sm_scale
        self.axis = axis

    def __call__(self, q, k, v):
        return ring_attention(q, k, v, causal=self.causal,
                              sm_scale=self.sm_scale, axis=self.axis)
