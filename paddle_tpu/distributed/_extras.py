"""Remaining paddle.distributed public names (reference:
python/paddle/distributed/__init__.py __all__): aliases, object
collectives, lifecycle helpers, gloo shims, and the parameter-server
dataset/entry surface (gated per DESIGN.md's PS descope).
"""
from __future__ import annotations

import pickle
from typing import List, Optional

from .communication import (all_gather, all_to_all,  # noqa: F401
                            all_to_all_single)

__all__ = ["alltoall", "alltoall_single", "gather", "split", "wait",
           "broadcast_object_list", "scatter_object_list",
           "destroy_process_group", "is_available", "ParallelMode",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
           "InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry"]


_obj_gen = [0]  # per-process round counter for object-collective keys


# reference keeps both spellings; alltoall* are the documented public ones
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    return all_to_all_single(out_tensor, in_tensor,
                             in_split_sizes=in_split_sizes,
                             out_split_sizes=out_split_sizes, group=group,
                             sync_op=sync_op)


class ParallelMode:
    """reference distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def is_available() -> bool:
    """Whether the distributed package can be used (reference
    distributed/parallel.py is_available)."""
    return True


def destroy_process_group(group=None):
    """Tear down group state (reference communication/group.py
    destroy_process_group). Groups here are mesh views with no OS
    resources; the registry entry (and the store, for the global group)
    is dropped."""
    from .parallel import _global_store, _initialized

    if group is None:
        _initialized[0] = False
        _global_store[0] = None
        try:
            from .topology import _GROUPS

            _GROUPS.clear()
        except Exception:
            pass
    else:
        try:
            from .topology import _GROUPS

            _GROUPS.pop(getattr(group, "id", None), None)
        except Exception:
            pass


def broadcast_object_list(object_list: List, src: int = 0, group=None):
    """Broadcast picklable python objects (reference
    communication/broadcast.py broadcast_object_list). Single-controller:
    every process in this runtime already holds src's objects; multi-host
    uses the TCP store."""
    import jax

    if jax.process_count() == 1:
        return object_list
    from .env import get_rank
    from .parallel import get_store

    store = get_store()
    if store is None:
        raise RuntimeError("broadcast_object_list needs init_parallel_env")
    # versioned key: the same (src) pair broadcasting twice must not let a
    # fast rank read the previous round's payload
    _obj_gen[0] += 1
    key = f"bcast_obj/{src}/{_obj_gen[0]}"
    if get_rank() == src:
        store.set(key, pickle.dumps(object_list).hex())
    store.wait(key)  # blocks until src publishes
    raw = store.get(key)
    raw = raw.decode() if isinstance(raw, bytes) else raw
    got = pickle.loads(bytes.fromhex(raw))
    object_list[:] = got
    return object_list


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group=None):
    """Scatter python objects (reference scatter_object_list)."""
    import jax

    from .env import get_rank, get_world_size

    if jax.process_count() == 1:
        world = max(1, get_world_size())
        objs = in_object_list or []
        per = max(1, len(objs) // world) if objs else 0
        out_object_list[:] = objs[:per] if objs else []
        return out_object_list
    from .parallel import get_store

    store = get_store()
    if store is None:
        raise RuntimeError("scatter_object_list needs init_parallel_env")
    _obj_gen[0] += 1
    key = f"scatter_obj/{src}/{_obj_gen[0]}"
    if get_rank() == src:
        store.set(key, pickle.dumps(in_object_list).hex())
    store.wait(key)
    raw = store.get(key)
    raw = raw.decode() if isinstance(raw, bytes) else raw
    objs = pickle.loads(bytes.fromhex(raw))
    world = max(1, get_world_size())
    per = len(objs) // world
    r = get_rank()
    out_object_list[:] = objs[r * per:(r + 1) * per]
    return out_object_list


def gather(tensor, gather_list=None, dst: int = 0, group=None,
           sync_op=True):
    """Gather tensors to dst (reference communication/gather.py). Under
    single-controller SPMD a host-side tensor is logically REPLICATED
    across the group, so the gathered list is nranks copies; the
    stacked-ranks eager form (leading dim == group size) is sliced."""
    from .communication.core import get_group
    from .env import get_rank

    g = get_group(group)
    n = max(1, g.nranks)
    v = tensor.value if hasattr(tensor, "value") else tensor
    if getattr(v, "shape", ()) and v.shape[0] == n:
        out: List = []
        all_gather(out, tensor, group=group, sync_op=sync_op)
    else:
        out = [tensor] * n  # replicated host value
    if gather_list is not None and get_rank() == dst:
        gather_list[:] = out
    return gather_list


def wait(tensor, group=None, use_calc_stream=True):
    """Block until the tensor's producing work completes (reference
    communication/wait.py). XLA orders work per device; the honest barrier
    is a block_until_ready on the value."""
    v = tensor.value if hasattr(tensor, "value") else tensor
    try:
        v.block_until_ready()
    except AttributeError:
        pass
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style split of an embedding/linear layer across the model
    -parallel group (reference distributed/collective.py split:39). Routes
    to the mpu layers — the mesh owns placement."""
    from .fleet.layers.mpu import mp_layers as mpu

    if operation == "embedding":
        layer = mpu.VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = mpu.RowParallelLinear(size[0], size[1],
                                          has_bias=bias_attr is not False,
                                          input_is_parallel=False)
        else:
            layer = mpu.ColumnParallelLinear(size[0], size[1],
                                             has_bias=bias_attr is not False,
                                             gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r}")


# -- gloo host-rendezvous shims (reference gloo wrappers exist to give CPU
#    processes a barrier; the TCP store plays that role here) --------------


def gloo_init_parallel_env(rank_id: int, rank_num: int, server_endpoint):
    import os

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    host, port = str(server_endpoint).rsplit(":", 1)
    os.environ.setdefault("MASTER_ADDR", host)
    os.environ.setdefault("MASTER_PORT", port)
    from .parallel import init_parallel_env

    init_parallel_env()


_gloo_barrier_gen = [0]


def gloo_barrier(timeout: float = 600.0):
    import os
    import time

    from .parallel import get_store

    store = get_store()
    if store is None:
        return  # single process: nothing to wait for
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    _gloo_barrier_gen[0] += 1
    key = f"gloo/barrier/{_gloo_barrier_gen[0]}"
    store.add(key, 1)
    deadline = time.time() + timeout
    while time.time() < deadline:  # add(key, 0) = non-blocking read
        if store.add(key, 0) >= world:
            return
        time.sleep(0.005)
    raise TimeoutError("gloo_barrier timed out")


def gloo_release():
    from .parallel import _global_store

    _global_store[0] = None


# -- parameter-server data surface (reference distributed/entry_attr.py +
#    fleet/dataset/dataset.py), backed by the real PS in distributed/ps ----


class EntryAttr:
    """Sparse-table entry-admission policy base (reference
    entry_attr.EntryAttr:47 — `_to_attr()` is the wire form the table
    config carries). Consumed by ``ps.TableConfig(entry=...)``: the shard
    applies the policy when a row is first pushed."""

    def _to_attr(self) -> str:
        raise NotImplementedError("use a concrete EntryAttr subclass")


class ProbabilityEntry(EntryAttr):
    """Admit a NEW row with probability p (reference entry_attr.py:57):
    rejected rows stay zero and their pushes are dropped — the CTR-table
    admission filter for ultra-long-tail ids."""

    def __init__(self, probability: float):
        if not 0.0 < probability <= 1.0:
            raise ValueError(
                f"probability must be in (0, 1], got {probability}")
        self._probability = float(probability)

    def _to_attr(self) -> str:
        return f"probability_entry:{self._probability}"


class CountFilterEntry(EntryAttr):
    """A row becomes stored/trainable only after it was pushed
    ``count_filter`` times (reference entry_attr.py:98); earlier pushes
    just bump the occurrence counter."""

    def __init__(self, count_filter: int):
        if count_filter < 1:
            raise ValueError(
                f"count_filter must be >= 1, got {count_filter}")
        self._count_filter = int(count_filter)

    def _to_attr(self) -> str:
        return f"count_filter_entry:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    """Declare the show/click stat slots a CTR table tracks per row
    (reference entry_attr.py:142); the shard accumulates them via
    ``PsClient.push_show_click``."""

    def __init__(self, show_name: str, click_name: str):
        self._show = str(show_name)
        self._click = str(click_name)

    def _to_attr(self) -> str:
        return f"show_click_entry:{self._show}:{self._click}"


def _parse_multislot(line: str):
    """One MultiSlot line -> {slot: np.ndarray}. Format (the PS pipeline
    wire form, fleet.MultiSlotDataGenerator): ``slot:len v1 .. vlen ...``"""
    import numpy as np

    toks = line.split()
    out = {}
    i = 0
    while i < len(toks):
        slot, n = toks[i].rsplit(":", 1)
        n = int(n)
        vals = toks[i + 1: i + 1 + n]
        try:
            arr = np.asarray([int(v) for v in vals], np.int64)
        except ValueError:
            arr = np.asarray([float(v) for v in vals], np.float32)
        out[slot] = arr
        i += 1 + n
    return out


class DatasetBase:
    """Reference fleet/dataset/dataset.py DatasetBase: filelist + batch
    config over the MultiSlot text format; ``pipe_command`` (when set)
    transforms each file's lines through a shell pipe, exactly the
    data-generator contract."""

    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._pipe_command = None
        self._use_var = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        self._use_var = list(use_var or [])
        self._pipe_command = pipe_command
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _read_file(self, path):
        if self._pipe_command:
            import subprocess

            proc = subprocess.run(
                self._pipe_command, shell=True,  # noqa: S602 - user cmd
                stdin=open(path, "rb"), capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pipe_command failed on {path}: {proc.stderr[:200]}")
            lines = proc.stdout.splitlines()
        else:
            with open(path) as f:
                lines = f.read().splitlines()
        return [_parse_multislot(ln) for ln in lines if ln.strip()]

    def _iter_samples(self):
        for path in self._filelist:
            yield from self._read_file(path)

    def _batches(self, samples):
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self._batch_size:
                yield buf
                buf = []
        if buf:
            yield buf


class InMemoryDataset(DatasetBase):
    """Reference InMemoryDataset (dataset.py:351): load files into
    memory, shuffle, iterate batches. ``global_shuffle`` on one host is
    the local shuffle (multi-host exchange belongs to the descoped brpc
    data plane; documented)."""

    def __init__(self):
        super().__init__()
        self._memory = None
        self._epoch_seed = 0

    def load_into_memory(self, is_shuffle: bool = False):
        self._memory = list(self._iter_samples())
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        if self._memory is None:
            raise RuntimeError("preload_into_memory was not called")

    def local_shuffle(self):
        import random

        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        rng = random.Random(self._epoch_seed)
        self._epoch_seed += 1
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None) -> int:
        return 0 if self._memory is None else len(self._memory)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return self.get_memory_data_size(fleet)

    def release_memory(self):
        self._memory = None

    def __iter__(self):
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        return self._batches(iter(self._memory))


class QueueDataset(DatasetBase):
    """Reference QueueDataset (dataset.py:1460-ish): STREAMS the filelist
    without materializing it; shuffle/in-memory ops raise, matching the
    reference's own NotImplementedError contract for this class."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams files; use InMemoryDataset to shuffle "
            "(the reference raises the same way)")

    def global_shuffle(self, fleet=None, thread_num=12):
        raise NotImplementedError(
            "QueueDataset streams files; use InMemoryDataset to shuffle "
            "(the reference raises the same way)")

    def __iter__(self):
        return self._batches(self._iter_samples())
