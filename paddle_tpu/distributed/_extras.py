"""Remaining paddle.distributed public names (reference:
python/paddle/distributed/__init__.py __all__): aliases, object
collectives, lifecycle helpers, gloo shims, and the parameter-server
dataset/entry surface (gated per DESIGN.md's PS descope).
"""
from __future__ import annotations

import pickle
from typing import List, Optional

from .communication import (all_gather, all_to_all,  # noqa: F401
                            all_to_all_single)

__all__ = ["alltoall", "alltoall_single", "gather", "split", "wait",
           "broadcast_object_list", "scatter_object_list",
           "destroy_process_group", "is_available", "ParallelMode",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
           "InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry"]


_obj_gen = [0]  # per-process round counter for object-collective keys


# reference keeps both spellings; alltoall* are the documented public ones
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    return all_to_all_single(out_tensor, in_tensor,
                             in_split_sizes=in_split_sizes,
                             out_split_sizes=out_split_sizes, group=group,
                             sync_op=sync_op)


class ParallelMode:
    """reference distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def is_available() -> bool:
    """Whether the distributed package can be used (reference
    distributed/parallel.py is_available)."""
    return True


def destroy_process_group(group=None):
    """Tear down group state (reference communication/group.py
    destroy_process_group). Groups here are mesh views with no OS
    resources; the registry entry (and the store, for the global group)
    is dropped."""
    from .parallel import _global_store, _initialized

    if group is None:
        _initialized[0] = False
        _global_store[0] = None
        try:
            from .topology import _GROUPS

            _GROUPS.clear()
        except Exception:
            pass
    else:
        try:
            from .topology import _GROUPS

            _GROUPS.pop(getattr(group, "id", None), None)
        except Exception:
            pass


def broadcast_object_list(object_list: List, src: int = 0, group=None):
    """Broadcast picklable python objects (reference
    communication/broadcast.py broadcast_object_list). Single-controller:
    every process in this runtime already holds src's objects; multi-host
    uses the TCP store."""
    import jax

    if jax.process_count() == 1:
        return object_list
    from .env import get_rank
    from .parallel import get_store

    store = get_store()
    if store is None:
        raise RuntimeError("broadcast_object_list needs init_parallel_env")
    # versioned key: the same (src) pair broadcasting twice must not let a
    # fast rank read the previous round's payload
    _obj_gen[0] += 1
    key = f"bcast_obj/{src}/{_obj_gen[0]}"
    if get_rank() == src:
        store.set(key, pickle.dumps(object_list).hex())
    store.wait(key)  # blocks until src publishes
    raw = store.get(key)
    raw = raw.decode() if isinstance(raw, bytes) else raw
    got = pickle.loads(bytes.fromhex(raw))
    object_list[:] = got
    return object_list


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group=None):
    """Scatter python objects (reference scatter_object_list)."""
    import jax

    from .env import get_rank, get_world_size

    if jax.process_count() == 1:
        world = max(1, get_world_size())
        objs = in_object_list or []
        per = max(1, len(objs) // world) if objs else 0
        out_object_list[:] = objs[:per] if objs else []
        return out_object_list
    from .parallel import get_store

    store = get_store()
    if store is None:
        raise RuntimeError("scatter_object_list needs init_parallel_env")
    _obj_gen[0] += 1
    key = f"scatter_obj/{src}/{_obj_gen[0]}"
    if get_rank() == src:
        store.set(key, pickle.dumps(in_object_list).hex())
    store.wait(key)
    raw = store.get(key)
    raw = raw.decode() if isinstance(raw, bytes) else raw
    objs = pickle.loads(bytes.fromhex(raw))
    world = max(1, get_world_size())
    per = len(objs) // world
    r = get_rank()
    out_object_list[:] = objs[r * per:(r + 1) * per]
    return out_object_list


def gather(tensor, gather_list=None, dst: int = 0, group=None,
           sync_op=True):
    """Gather tensors to dst (reference communication/gather.py). Under
    single-controller SPMD a host-side tensor is logically REPLICATED
    across the group, so the gathered list is nranks copies; the
    stacked-ranks eager form (leading dim == group size) is sliced."""
    from .communication.core import get_group
    from .env import get_rank

    g = get_group(group)
    n = max(1, g.nranks)
    v = tensor.value if hasattr(tensor, "value") else tensor
    if getattr(v, "shape", ()) and v.shape[0] == n:
        out: List = []
        all_gather(out, tensor, group=group, sync_op=sync_op)
    else:
        out = [tensor] * n  # replicated host value
    if gather_list is not None and get_rank() == dst:
        gather_list[:] = out
    return gather_list


def wait(tensor, group=None, use_calc_stream=True):
    """Block until the tensor's producing work completes (reference
    communication/wait.py). XLA orders work per device; the honest barrier
    is a block_until_ready on the value."""
    v = tensor.value if hasattr(tensor, "value") else tensor
    try:
        v.block_until_ready()
    except AttributeError:
        pass
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style split of an embedding/linear layer across the model
    -parallel group (reference distributed/collective.py split:39). Routes
    to the mpu layers — the mesh owns placement."""
    from .fleet.layers.mpu import mp_layers as mpu

    if operation == "embedding":
        layer = mpu.VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = mpu.RowParallelLinear(size[0], size[1],
                                          has_bias=bias_attr is not False,
                                          input_is_parallel=False)
        else:
            layer = mpu.ColumnParallelLinear(size[0], size[1],
                                             has_bias=bias_attr is not False,
                                             gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r}")


# -- gloo host-rendezvous shims (reference gloo wrappers exist to give CPU
#    processes a barrier; the TCP store plays that role here) --------------


def gloo_init_parallel_env(rank_id: int, rank_num: int, server_endpoint):
    import os

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    host, port = str(server_endpoint).rsplit(":", 1)
    os.environ.setdefault("MASTER_ADDR", host)
    os.environ.setdefault("MASTER_PORT", port)
    from .parallel import init_parallel_env

    init_parallel_env()


_gloo_barrier_gen = [0]


def gloo_barrier(timeout: float = 600.0):
    import os
    import time

    from .parallel import get_store

    store = get_store()
    if store is None:
        return  # single process: nothing to wait for
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    _gloo_barrier_gen[0] += 1
    key = f"gloo/barrier/{_gloo_barrier_gen[0]}"
    store.add(key, 1)
    deadline = time.time() + timeout
    while time.time() < deadline:  # add(key, 0) = non-blocking read
        if store.add(key, 0) >= world:
            return
        time.sleep(0.005)
    raise TimeoutError("gloo_barrier timed out")


def gloo_release():
    from .parallel import _global_store

    _global_store[0] = None


# -- parameter-server surface (descoped subsystem — DESIGN.md): the names
#    exist and explain themselves instead of AttributeError-ing ------------

_PS_MSG = ("the brpc parameter-server stack is deliberately out of scope "
           "for this TPU-native build (synchronous SPMD + sharded "
           "embeddings replace async PS; see DESIGN.md 'Descoped "
           "subsystems')")


class _PSGated:
    def __init__(self, *a, **kw):
        raise NotImplementedError(f"{type(self).__name__}: {_PS_MSG}")


class InMemoryDataset(_PSGated):
    pass


class QueueDataset(_PSGated):
    pass


class CountFilterEntry(_PSGated):
    pass


class ProbabilityEntry(_PSGated):
    pass


class ShowClickEntry(_PSGated):
    pass
