from .main import launch, main

__all__ = ["launch", "main"]
