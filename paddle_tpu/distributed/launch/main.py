"""Process launcher CLI (reference: python/paddle/distributed/launch/main.py:18,
CollectiveController run loop launch/controllers/collective.py:268,
HTTPMaster rendezvous controllers/master.py:73).

Usage:  python -m paddle_tpu.distributed.launch [--nnodes N] [--node_rank R]
            [--nproc_per_node P] [--master HOST:PORT] [--log_dir DIR]
            [--elastic_level L] [--max_restarts K] training_script [args...]

TPU-native notes: a TPU host normally runs ONE process owning all local
chips (nproc_per_node=1 default); the reference's per-GPU process model is
still supported for CPU simulation (each proc limited via JAX flags). The
rank-0 TCP store (native C++ TCPStore) plays the HTTPMaster role; each
child gets the reference env contract (PADDLE_TRAINER_ID,
PADDLE_TRAINER_ENDPOINTS, MASTER_ADDR/PORT, PADDLE_NNODES).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main"]

ELASTIC_EXIT_CODE = 101  # reference fleet/elastic/manager.py:30


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-process / multi-node launcher")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--elastic_level", type=int, default=0)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids for this node")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args, local_rank: int, generation: int = 0) -> dict:
    env = dict(os.environ)
    world = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    master = args.master or "127.0.0.1:0"
    host, _, port = master.partition(":")
    env.update({
        # restart generation: ElasticManager scopes its store keys by this
        # so a relaunched world starts from clean membership counters
        "PADDLE_ELASTIC_GENERATION": str(generation),
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_TRAINER_ENDPOINTS": master,
        "MASTER_ADDR": host or "127.0.0.1",
        "MASTER_PORT": port or "0",
    })
    if args.devices:
        env["CUDA_VISIBLE_DEVICES"] = args.devices
        env["TPU_VISIBLE_DEVICES"] = args.devices
    return env


def launch(argv: Optional[List[str]] = None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    os.makedirs(args.log_dir, exist_ok=True)
    restarts = 0
    while True:
        procs: List[subprocess.Popen] = []
        logs = []
        for lr in range(args.nproc_per_node):
            rank = args.node_rank * args.nproc_per_node + lr
            log = open(os.path.join(args.log_dir,
                                    f"workerlog.{rank}"), "ab")
            logs.append(log)
            cmd = [sys.executable, args.script] + args.script_args
            procs.append(subprocess.Popen(
                cmd, env=_child_env(args, lr, generation=restarts),
                stdout=log, stderr=log))

        # watch loop (≙ CollectiveController.run :268)
        fail_code = 0
        try:
            while procs:
                alive = []
                for p in procs:
                    rc = p.poll()
                    if rc is None:
                        alive.append(p)
                    elif rc != 0:
                        fail_code = rc
                        break
                if fail_code:
                    break
                if not alive:
                    break
                procs = alive
                time.sleep(0.2)
        except KeyboardInterrupt:
            fail_code = -signal.SIGINT
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            for log in logs:
                log.close()

        if fail_code == 0:
            return 0
        if (args.elastic_level > 0 and restarts < args.max_restarts
                and fail_code in (ELASTIC_EXIT_CODE, 1)):
            restarts += 1
            print(f"[launch] child failed (code {fail_code}); restart "
                  f"{restarts}/{args.max_restarts}", file=sys.stderr)
            continue
        return int(fail_code) if fail_code > 0 else 1


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
