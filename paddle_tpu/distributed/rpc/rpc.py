"""RPC runtime (reference: python/paddle/distributed/rpc/rpc.py — init_rpc
over a master TCP store, rpc_sync/rpc_async by worker name, shutdown with a
never-timeout barrier).

TPU-native/zero-dep: the reference delegates transport to brpc; here each
worker runs a small threaded TCP server executing pickled (fn, args,
kwargs) requests, and the rendezvous (name -> ip:port registry + barriers)
rides the framework's native TCPStore — the same store the collective
bring-up uses. Single-host multiprocess and multi-host work identically.

Security note (same contract as the reference): RPC endpoints execute
pickled callables from registered peers — run it only on trusted networks,
never exposed publicly.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = 30.0

_state = {
    "store": None,
    "self": None,          # WorkerInfo
    "workers": {},         # name -> WorkerInfo
    "server": None,
    "server_thread": None,
    "pool": None,
    "world_size": 0,
}


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed mid-message")
        buf += chunk
    return bytes(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = pickle.loads(_recv_msg(self.request))
            fn, args, kwargs = req
            try:
                result = ("ok", fn(*args, **kwargs))
            except Exception as e:  # noqa: BLE001 — error travels back
                result = ("err", e)
            _send_msg(self.request, pickle.dumps(result))
        except (ConnectionError, EOFError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _host_ip(world_size: int) -> str:
    """The address peers should dial. Loopback only works single-host;
    multi-host advertises the interface that routes externally."""
    if world_size <= 1:
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))  # no packet sent; picks the route
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Start this worker's RPC service and exchange worker infos
    (reference rpc.py:73)."""
    import os

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:29401")

    server = _Server(("0.0.0.0", 0), _Handler)
    ip = _host_ip(world_size)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    me = WorkerInfo(name, rank, ip, port)
    workers: Dict[str, WorkerInfo] = {}
    if world_size > 1:
        from ...native import TCPStore

        host, sport = master_endpoint.rsplit(":", 1)
        store = TCPStore(host, int(sport), is_master=(rank == 0),
                         world_size=world_size)
        _state["store"] = store
        store.set(f"rpc/{rank}", pickle.dumps(tuple(me)).hex())
        for r in range(world_size):
            raw = store.get(f"rpc/{r}")  # blocks until the key appears
            raw = raw.decode() if isinstance(raw, bytes) else raw
            info = WorkerInfo(*pickle.loads(bytes.fromhex(raw)))
            workers[info.name] = info
    else:
        workers[name] = me

    _state.update(self=me, workers=workers, server=server,
                  server_thread=thread, world_size=world_size,
                  pool=ThreadPoolExecutor(max_workers=8))


def _invoke(to: str, fn, args, kwargs, timeout):
    info = get_worker_info(to)
    if info is None:
        raise ValueError(f"unknown rpc worker {to!r}")
    sock = socket.create_connection((info.ip, info.port),
                                    timeout=timeout if timeout > 0 else None)
    try:
        _send_msg(sock, pickle.dumps((fn, args or (), kwargs or {})))
        status, payload = pickle.loads(_recv_msg(sock))
    finally:
        sock.close()
    if status == "err":
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking remote call (reference rpc.py:141)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout=_DEFAULT_RPC_TIMEOUT) -> Future:
    """Non-blocking remote call; returns a Future with .wait()/.result()
    (reference rpc.py:179 returns a FutureWrapper with wait())."""
    if _state["pool"] is None:
        raise RuntimeError("init_rpc must be called first")
    fut = _state["pool"].submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # reference API compatibility
    return fut


def _barrier(tag: str, timeout: float = 600.0):
    """Counter-based barrier over the store. ``store.get`` blocks
    server-side on missing keys, so the polls use ``add(key, 0)`` — a
    non-blocking read that also creates the key — keeping the deadline
    live even when a peer never arrives."""
    store = _state["store"]
    if store is None:
        return
    world = _state["world_size"]
    key = f"rpc/barrier/{tag}"
    store.add(key, 1)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if store.add(key, 0) >= world:
            return
        time.sleep(0.01)
    raise TimeoutError(f"rpc barrier {tag} timed out")


def shutdown():
    """Block until every worker reaches shutdown, then stop serving
    (reference rpc.py:270 '_barrier_never_timeout then stop')."""
    if _state["server"] is None:
        return
    store = _state["store"]
    me = _state["self"]
    world = _state["world_size"]
    _barrier("shutdown")
    if store is not None:
        # ordered teardown: rank 0 owns the in-process store server and
        # must outlive every peer's final barrier poll — non-masters ack
        # departure, the master waits for all acks before closing
        if me.rank != 0:
            try:
                store.add("rpc/barrier/departed", 1)
            except Exception:
                pass
        else:
            deadline = time.time() + 600
            while time.time() < deadline:
                try:
                    if store.add("rpc/barrier/departed", 0) >= world - 1:
                        break
                except Exception:
                    break
                time.sleep(0.01)
    _state["server"].shutdown()
    _state["server"].server_close()
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=False)
    _state.update(server=None, server_thread=None, pool=None, workers={},
                  self=None, store=None, world_size=0)


def get_worker_info(name: str) -> Optional[WorkerInfo]:
    return _state["workers"].get(name)


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> Optional[WorkerInfo]:
    return _state["self"]
