"""DistributedStrategy — typed config tree.

Reference: fleet/base/distributed_strategy.py (2.6K LoC protobuf wrapper over
framework/distributed_strategy.proto; HybridConfig at proto:69-76). TPU-native
redesign per SURVEY.md §5.6: plain dataclass-style tree + FLAGS_-style env
override; keeps the hybrid degrees {dp, mp, pp, sharding(+stage), sp, ep}.
"""
from __future__ import annotations

import copy
from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sp_degree": 1,
    "ep_degree": 1,
    "order": ["dp", "pp", "sharding", "sp", "ep", "mp"],
}


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (reference HybridConfig, proto:69-76)
        self.hybrid_configs: Dict[str, Any] = copy.deepcopy(_DEFAULT_HYBRID)
        self.hybrid_parallel_order = list(_DEFAULT_HYBRID["order"])
        # AMP (reference amp sub-config)
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0,
            "use_dynamic_loss_scaling": True,
            "custom_white_list": [],
            "custom_black_list": [],
            "use_pure_fp16": False,
            "use_pure_bf16": False,
        }
        # recompute
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        # sharding (ZeRO)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {
            "stage": 1, "degree": 1, "offload": False,
            "accumulate_steps": 1,
        }
        # pipeline
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1, "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        # gradient merge / accumulation
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # misc toggles kept for parity
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01,
                             "exclude_from_weight_decay": []}
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001,
                             "lars_weight_decay": 0.0005,
                             "epsilon": 0.0,
                             "exclude_from_weight_decay": []}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fp16_allreduce = False
        self.heter_ccl_mode = False
        self.without_graph_optimization = True

    def _set_hybrid(self, **kw):
        self.hybrid_configs.update(kw)

    @property
    def hybrid_configs_degrees(self):
        h = self.hybrid_configs
        return (h["dp_degree"], h["pp_degree"], h["sharding_degree"],
                h["mp_degree"], h.get("sp_degree", 1), h.get("ep_degree", 1))

    def __setattr__(self, k, v):
        # hybrid_configs accepts partial-dict assignment like the reference
        if k == "hybrid_configs" and isinstance(v, dict) and hasattr(self, "hybrid_configs"):
            merged = copy.deepcopy(_DEFAULT_HYBRID)
            merged.update(self.__dict__.get("hybrid_configs", {}))
            merged.update(v)
            self.__dict__[k] = merged
            return
        self.__dict__[k] = v

    def __repr__(self):
        h = self.hybrid_configs
        return (f"DistributedStrategy(dp={h['dp_degree']}, mp={h['mp_degree']}, "
                f"pp={h['pp_degree']}, sharding={h['sharding_degree']}, "
                f"amp={self.amp}, recompute={self.recompute})")
