from .distributed_strategy import DistributedStrategy

__all__ = ["DistributedStrategy"]
