"""paddle.distributed.fleet parity (fleet/fleet.py:167,1044; fleet/model.py:30).

TPU-native: ``fleet.init`` builds the global hybrid Mesh from
DistributedStrategy degrees and installs an HybridCommunicateGroup view over
it; ``distributed_model``/``distributed_optimizer`` pick the same wrapper
taxonomy as the reference (DP/TP/PP/sharding), each of which maps to mesh
shardings rather than per-process comm groups.
"""
from __future__ import annotations

import os
from typing import Optional

from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        build_mesh, get_mesh, set_mesh)
from .base.distributed_strategy import DistributedStrategy
from . import meta_parallel  # noqa: F401
from .layers import mpu  # noqa: F401

__all__ = ["init", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "meta_parallel", "mpu", "utils"]

_fleet_state = {"initialized": False, "hcg": None, "strategy": None,
                "role_maker": None, "ps_client": None, "ps_server": None}


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None):
    """fleet/fleet.py:167 parity. Builds the hybrid mesh from strategy
    degrees (defaults: whole world on dp). A parameter-server role maker
    (``PaddleCloudRoleMaker(is_collective=False)``) switches fleet into
    PS mode instead: servers then call ``init_server()``/``run_server()``
    and trainers ``init_worker()`` (reference the_one_ps.py flow)."""
    import jax

    if role_maker is not None and not getattr(
            role_maker, "_is_collective", True):
        _fleet_state.update(initialized=True, role_maker=role_maker,
                            ps_client=None, ps_server=None, hcg=None,
                            strategy=strategy)
        return
    # a collective init must fully leave PS mode (test suites reuse the
    # process): stale role makers would flip is_server()/is_worker()
    _fleet_state.update(role_maker=role_maker, ps_client=None,
                        ps_server=None)

    strategy = strategy or DistributedStrategy()
    h = strategy.hybrid_configs
    try:
        mesh = build_mesh(dp=h["dp_degree"], pp=h["pp_degree"],
                          sharding=h["sharding_degree"], mp=h["mp_degree"],
                          sp=h.get("sp_degree", 1), ep=h.get("ep_degree", 1))
    except ValueError:
        if int(os.environ.get("FLEET_STRICT_MESH", "0")):
            raise
        mesh = build_mesh()  # degrees don't fit this host: all-dp fallback
    set_mesh(mesh)
    hcg = HybridCommunicateGroup(mesh=mesh)
    _fleet_state.update(initialized=True, hcg=hcg, strategy=strategy)
    return


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _fleet_state["hcg"] is None:
        init()
    return _fleet_state["hcg"]


def distributed_model(model):
    """fleet/model.py:30 parity — wrap by parallel mode."""
    from .meta_parallel.tensor_parallel import TensorParallel

    hcg = get_hybrid_communicate_group()
    strategy = _fleet_state["strategy"] or DistributedStrategy()
    if hcg.get_pipe_parallel_world_size() > 1:
        from .meta_parallel.pipeline_parallel import (
            PipelineParallel, PipelineParallelWithInterleave)

        # reference model.py:162-169: interleave when virtual stages > 1
        if getattr(model, "get_num_virtual_stages", lambda: 1)() > 1:
            return PipelineParallelWithInterleave(model, hcg, strategy)
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_sharding_parallel_world_size() > 1:
        from .meta_parallel.sharding_parallel import ShardingParallel

        return ShardingParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        from ..parallel import DataParallel

        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """fleet/fleet.py:1044 parity — HybridParallelOptimizer when any hybrid
    dim is active; sharding stage-1 optimizer when sharding_degree>1."""
    from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
        HybridParallelOptimizer)

    hcg = get_hybrid_communicate_group()
    strategy = strategy or _fleet_state["strategy"] or DistributedStrategy()
    # strategy-driven meta-optimizer transforms (reference meta_optimizers/
    # passes; innermost closest to the raw optimizer)
    if getattr(strategy, "lamb", False):
        from ...optimizer import Lamb

        cfg = getattr(strategy, "lamb_configs", {}) or {}
        exclude = cfg.get("exclude_from_weight_decay") or []
        optimizer = Lamb(
            learning_rate=optimizer._lr,  # keeps an LRScheduler live
            lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
            parameters=optimizer._parameter_list,
            grad_clip=getattr(optimizer, "_grad_clip", None),
            exclude_from_weight_decay_fn=(
                (lambda p: any(tok in (p.name or "") for tok in exclude))
                if exclude else None))
    if getattr(strategy, "lars", False):
        from .meta_optimizers import LarsMomentumOptimizer

        cfg = getattr(strategy, "lars_configs", {}) or {}
        optimizer = LarsMomentumOptimizer(
            learning_rate=optimizer._lr,
            momentum=cfg.get("momentum", 0.9),
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            epsilon=cfg.get("epsilon", 0.0),
            parameters=optimizer._parameter_list,
            grad_clip=getattr(optimizer, "_grad_clip", None),
            exclude_from_weight_decay=cfg.get(
                "exclude_from_weight_decay", None))
    if getattr(strategy, "dgc", False):
        from ...optimizer.optimizer import Momentum, SGD
        from .meta_optimizers import DGCMomentumOptimizer

        if not isinstance(optimizer, (Momentum, SGD)):
            # DGC REPLACES the momentum rule; silently discarding Adam's
            # adaptive moments would train a different optimizer
            raise TypeError(
                "strategy.dgc requires a Momentum/SGD optimizer (got "
                f"{type(optimizer).__name__}); the reference DGC optimizer "
                "has the same constraint")
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        sp = cfg.get("sparsity", [0.999])
        optimizer = DGCMomentumOptimizer.from_momentum(
            optimizer,
            sparsity=sp[-1] if isinstance(sp, (list, tuple)) else sp,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1))
    if getattr(strategy, "fp16_allreduce", False):
        from .meta_optimizers import FP16AllReduceOptimizer

        optimizer = FP16AllReduceOptimizer(optimizer)
    if getattr(strategy, "localsgd", False):
        from .meta_optimizers import LocalSGDOptimizer

        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(optimizer,
                                      k_steps=cfg.get("k_steps", 1),
                                      begin_step=cfg.get("begin_step", 1),
                                      hcg=hcg)
    if getattr(strategy, "gradient_merge", False):
        from .meta_optimizers import GradientMergeOptimizer

        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        optimizer = GradientMergeOptimizer(optimizer,
                                           k_steps=cfg.get("k_steps", 1),
                                           avg=cfg.get("avg", True))
    if hcg.get_sharding_parallel_world_size() > 1:
        # stage-1 state sharding under the hybrid wrapper (reference
        # fleet.py:1044 composes DygraphShardingOptimizer the same way)
        from .meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer \
            import DygraphShardingOptimizer

        optimizer = DygraphShardingOptimizer(optimizer, hcg)
    return HybridParallelOptimizer(optimizer, hcg, strategy)


def worker_num() -> int:
    import jax

    return jax.process_count()


def worker_index() -> int:
    import jax

    return jax.process_index()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    from .. import barrier

    try:
        barrier()
    except Exception:
        pass


def __getattr__(name):
    if name in ("utils", "recompute"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)


# -- role makers + Fleet class surface (reference fleet/base/role_maker.py,
#    fleet/fleet.py) --------------------------------------------------------


class Role:
    """reference role_maker.Role constants."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Env-driven role maker (reference role_maker.PaddleCloudRoleMaker).

    ``is_collective=False`` reads the parameter-server env contract
    (reference role_maker.py _ps_env): TRAINING_ROLE (PSERVER|TRAINER),
    PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINERS_NUM,
    PADDLE_TRAINER_ID, and for servers POD_IP:PADDLE_PORT to locate this
    node in the server list."""

    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = bool(is_collective)
        if self._is_collective:
            return
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._ps_role = (Role.SERVER if role == "PSERVER" else Role.WORKER)
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in eps.split(",") if e]
        if not self._server_endpoints:
            raise ValueError(
                "PS mode needs PADDLE_PSERVERS_IP_PORT_LIST "
                "(reference role_maker._ps_env contract)")
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        if self._ps_role == Role.SERVER:
            me = (f"{os.environ.get('POD_IP', '127.0.0.1')}:"
                  f"{os.environ['PADDLE_PORT']}")
            if me not in self._server_endpoints:
                raise ValueError(
                    f"this server's endpoint {me!r} (POD_IP:PADDLE_PORT; "
                    f"POD_IP defaults to 127.0.0.1) is not in "
                    f"PADDLE_PSERVERS_IP_PORT_LIST "
                    f"{self._server_endpoints} — the strings must match "
                    "exactly (hostname vs IP mismatches included)")
            self._server_index = self._server_endpoints.index(me)

    def _worker_num(self):
        if not self._is_collective:
            return self._trainers_num
        import jax

        return jax.process_count()

    worker_num = _worker_num

    def _worker_index(self):
        if not self._is_collective:
            return self._trainer_id
        import jax

        return jax.process_index()

    worker_index = _worker_index

    def _role(self):
        return getattr(self, "_ps_role", Role.WORKER)

    def _is_worker(self):
        return self._role() == Role.WORKER

    is_worker = _is_worker

    def _is_server(self):
        return self._role() == Role.SERVER

    is_server = _is_server

    def _is_first_worker(self):
        return self._worker_index() == 0

    is_first_worker = _is_first_worker


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference role_maker.UserDefinedRoleMaker — explicit rank/world/
    role, NO env parsing (unlike the parent's PS env contract)."""

    def __init__(self, is_collective: bool = True, init_gloo: bool = False,
                 current_id: int = 0, worker_num: int = 1, role=None,
                 server_endpoints=None, **kwargs):
        # deliberately NOT super().__init__: explicit args replace the env
        self._is_collective = bool(is_collective)
        self._id = int(current_id)
        self._num = int(worker_num)
        if not self._is_collective:
            self._ps_role = role if role is not None else Role.WORKER
            self._server_endpoints = list(server_endpoints or [])
            self._trainers_num = self._num
            self._trainer_id = self._id
            if self._ps_role == Role.SERVER:
                self._server_index = self._id

    def _worker_index(self):
        return self._id

    worker_index = _worker_index

    def _worker_num(self):
        return self._num

    worker_num = _worker_num


class UtilBase:
    """reference fleet/base/util_factory.UtilBase — small cross-worker
    utilities over the collective backend."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """Cross-WORKER (process-level) reduction — host values are
        per-process, so the world is jax.process_count(), not the device
        mesh; single process = identity."""
        import jax
        import numpy as np

        out = np.asarray(input)
        if jax.process_count() == 1:
            return out
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(out))
        if mode == "sum":
            return gathered.sum(0)
        if mode == "mean":
            return gathered.mean(0)
        if mode == "min":
            return gathered.min(0)
        if mode == "max":
            return gathered.max(0)
        raise ValueError(f"unknown mode {mode!r}")

    def barrier(self, comm_world="worker"):
        from ... import distributed as dist

        dist.barrier()

    def all_gather(self, input, comm_world="worker"):
        import jax
        import numpy as np

        if jax.process_count() == 1:
            return [np.asarray(input)]
        from jax.experimental import multihost_utils

        g = np.asarray(multihost_utils.process_allgather(
            np.asarray(input)))
        return [g[i] for i in range(g.shape[0])]

    def get_file_shard(self, files):
        import jax

        n, i = jax.process_count(), jax.process_index()
        return [f for j, f in enumerate(sorted(files)) if j % n == i]

    def print_on_rank(self, message, rank_id=0):
        import jax

        if jax.process_index() == rank_id:
            print(message)


class Fleet:
    """Instantiable Fleet facade (reference fleet/fleet.py Fleet class —
    the module-level fleet.* functions are the bound methods of a
    singleton; this class gives the constructor surface)."""

    def __init__(self):
        self._util = UtilBase()

    def init(self, role_maker=None, is_collective=False, strategy=None):
        return init(role_maker=role_maker, is_collective=is_collective,
                    strategy=strategy)

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    @property
    def util(self):
        return self._util

    def worker_num(self):
        return worker_num()

    def worker_index(self):
        return worker_index()

    def is_first_worker(self):
        return is_first_worker()

    def barrier_worker(self):
        from ... import distributed as dist

        dist.barrier()

    def is_server(self):
        return is_server()

    def is_worker(self):
        return is_worker()

    def init_server(self, *a, **kw):
        return init_server(*a, **kw)

    def run_server(self):
        return run_server()

    def init_worker(self, *a, **kw):
        return init_worker(*a, **kw)

    def stop_worker(self):
        return stop_worker()


# -- parameter-server role flow (reference fleet.init_server/run_server/
#    init_worker over distributed/ps/the_one_ps.py; our PS lives in
#    distributed/ps/__init__.py) ---------------------------------------------


def _ps_role_maker():
    rm = _fleet_state.get("role_maker")
    if rm is None or getattr(rm, "_is_collective", True):
        raise RuntimeError(
            "fleet is not in parameter-server mode; call fleet.init("
            "PaddleCloudRoleMaker(is_collective=False)) under the PS env "
            "contract first")
    return rm


def is_server() -> bool:
    rm = _fleet_state.get("role_maker")
    return bool(rm is not None and not getattr(rm, "_is_collective", True)
                and rm.is_server())


def is_worker() -> bool:
    rm = _fleet_state.get("role_maker")
    if rm is None or getattr(rm, "_is_collective", True):
        return True
    return rm.is_worker()


def _ps_plane():
    """Data-plane selection (must be consistent across the server group
    and all trainers, and save formats are per-plane):

    - ``PADDLE_PS_DATA_PLANE=native`` — the C++ plane (ps/native.py over
      native/src/ps_table.cc, binary wire protocol — the brpc-analog hot
      path for plain tables).
    - ``PADDLE_PS_DATA_PLANE=python`` — the full-featured numpy plane
      (entry-admission policies, show/click accessors). Its transport is
      pickle-over-TCP: TRUSTED NETWORKS ONLY.
    - default (``auto``): native when the toolchain built it — plain
      tables shouldn't pay pickling, and the native plane raises loudly
      (pointing back here) if an accessor-feature table is requested.
      When the build is UNAVAILABLE, auto falls back to python ONLY for
      a single-node group (one server endpoint, one trainer — both
      planes live in this process, nothing can desync); any multi-node
      group raises instead of silently falling back, because the
      selection must resolve identically on every node (a node-local
      fallback would let a toolchain-less trainer pickle into peers'
      binary-protocol servers and die with an opaque EOF) — pin the
      plane via the env var cluster-wide."""
    import os

    plane = os.environ.get("PADDLE_PS_DATA_PLANE", "auto")
    if plane == "auto":
        plane = _ps_plane._auto  # one build probe per process: the
        if plane is None:        # g++ compile behind lib_path() can
            from ... import native as native_lib  # take ~2 min cold

            plane = "native" if native_lib.lib_path() else "unavailable"
            _ps_plane._auto = plane
        if plane == "unavailable":
            if _ps_single_node_group():
                # one local server + one trainer: the only other
                # participant runs on this same host, which failed the
                # same native build probe in any same-venv launch —
                # g++-less laptops keep working. Caveat (hence the
                # warning): server and trainer PROCESSES launched from
                # DIFFERENT python envs on one host can still resolve
                # differently; pin PADDLE_PS_DATA_PLANE to be safe.
                import warnings

                warnings.warn(
                    "PADDLE_PS_DATA_PLANE=auto: native data plane "
                    "unavailable (no g++); single-node group falls "
                    "back to the python plane. If the server and "
                    "trainer run from different python environments, "
                    "set PADDLE_PS_DATA_PLANE=python explicitly for "
                    "both.", RuntimeWarning, stacklevel=3)
                plane = "python"
            else:
                raise RuntimeError(
                    "PADDLE_PS_DATA_PLANE=auto: the native data plane "
                    "did not build on this node (g++ missing or compile "
                    "failed) — other nodes may still pick native, and "
                    "mixed planes fail with opaque stream errors. Set "
                    "PADDLE_PS_DATA_PLANE=python (or =native) "
                    "identically on every server and trainer node "
                    "(single-node groups fall back to python "
                    "automatically)")
    if plane == "native":
        from ..ps.native import NativePsClient, NativePsServer

        return NativePsServer, NativePsClient
    if plane != "python":
        # a typo must not silently engage the pickle transport (and
        # desync from peers that resolved the value correctly)
        raise ValueError(
            f"PADDLE_PS_DATA_PLANE={plane!r}: expected 'auto', 'native' "
            "or 'python'")
    from ..ps import PsClient, PsServer

    return PsServer, PsClient


_ps_plane._auto = None  # memoized auto-mode probe result


def _ps_single_node_group() -> bool:
    """True when the PS group is one server endpoint + one trainer AND
    that server endpoint is THIS host — the only configuration where a
    node-local plane fallback cannot create a mixed-plane cluster. A
    1-server/1-trainer group whose server lives on another machine still
    resolves the plane independently per node, so it gets the loud
    multi-node error, not the fallback."""
    import socket

    rm = _fleet_state.get("role_maker")
    if rm is None or getattr(rm, "_is_collective", True):
        return False
    try:
        if (len(rm._server_endpoints) != 1
                or int(rm._worker_num()) > 1):
            return False
        host = rm._server_endpoints[0].rsplit(":", 1)[0]
        if not host:
            # a malformed ':port' endpoint must hit the loud error, not
            # accidentally classify as local via an unset POD_IP
            return False
        local = {"127.0.0.1", "localhost", "0.0.0.0", "::1",
                 socket.gethostname()}
        pod_ip = os.environ.get("POD_IP")
        if pod_ip:
            local.add(pod_ip)
        try:
            local.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        return host in local
    except Exception:
        return False


def init_server(*args, **kwargs):
    """Build this node's PsServer shard (reference fleet.init_server).
    An optional ``dirname`` restores tables previously written by
    ``PsClient.save`` (the reference's load-model-on-init contract).
    Binds the port from the env contract; run_server() serves."""
    PsServer, _ = _ps_plane()

    rm = _ps_role_maker()
    ep = rm._server_endpoints[rm._server_index]
    host, port = ep.rsplit(":", 1)
    srv = PsServer(rm._server_index, len(rm._server_endpoints),
                   port=int(port), host=host)
    dirname = args[0] if args else (kwargs.get("dirname")
                                    or kwargs.get("model_dir"))
    if dirname:
        srv.load_model(dirname)
    _fleet_state["ps_server"] = srv
    return srv


def run_server():
    """Serve until a trainer sends stop (reference fleet.run_server)."""
    srv = _fleet_state.get("ps_server") or init_server()
    srv.run()


def init_worker(*args, **kwargs):
    """Connect this trainer to the server group (reference
    fleet.init_worker); the PsClient is then available via
    fleet.get_ps_client() and used by DistributedEmbedding."""
    _, PsClient = _ps_plane()

    rm = _ps_role_maker()
    client = PsClient(rm._server_endpoints)
    _fleet_state["ps_client"] = client
    return client


def get_ps_client():
    client = _fleet_state.get("ps_client")
    if client is None:
        raise RuntimeError("call fleet.init_worker() first")
    return client


def stop_worker():
    """Disconnect; the LAST trainer also stops the servers (reference
    fleet.stop_worker barrier-then-shutdown)."""
    rm = _ps_role_maker()
    client = _fleet_state.get("ps_client")
    if client is None:
        return
    pos = client.barrier("stop_worker", world=rm._trainers_num)
    if pos == rm._trainers_num:      # LAST arrival shuts the servers down
        client.stop_servers()
    client.close()
    _fleet_state["ps_client"] = None


class MultiSlotDataGenerator:
    """reference distributed/fleet/data_generator — the PS pipeline's
    line-oriented sample format: ``generate_sample`` yields
    (slot_name, [ids...]) pairs per input line; ``run_from_stdin`` emits
    the wire form ``slot:len id...``."""

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement "
            "generate_sample(line) -> iterable of (slot, values)")

    def _format(self, sample) -> str:
        parts = []
        for slot, values in sample:
            vals = list(values)
            parts.append(f"{slot}:{len(vals)} "
                         + " ".join(str(v) for v in vals))
        return " ".join(parts)

    def run_from_stdin(self):
        import sys as _sys

        for line in _sys.stdin:
            gen = self.generate_sample(line)
            for sample in (gen() if callable(gen) else gen):
                _sys.stdout.write(self._format(sample) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass


__all__ += ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
            "UtilBase", "Fleet", "CommunicateTopology",
            "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
            "is_server", "is_worker", "init_server", "run_server",
            "init_worker", "stop_worker", "get_ps_client"]
