"""FP16AllReduce — exchange gradients in half precision.

Reference analog: fleet/meta_optimizers/fp16_allreduce_optimizer.py (casts
grads fp32→fp16 before c_allreduce, back after). TPU-native: the dp
exchange is an XLA collective whose wire dtype IS the array dtype, so the
transform rounds the gradient through bf16 (the TPU half format) at step
time — same bandwidth halving, same quantization semantics.
"""
from __future__ import annotations

import jax.numpy as jnp

from .base import MetaOptimizerWrapper

__all__ = ["FP16AllReduceOptimizer"]


class FP16AllReduceOptimizer(MetaOptimizerWrapper):
    def __init__(self, inner_optimizer, dtype=jnp.bfloat16):
        super().__init__(inner_optimizer)
        self._dtype = dtype

    def step(self):
        from ....core.tensor import Tensor

        for p, g in self._inner_opt._collect_params_grads():
            if g is None:
                continue
            p.grad = Tensor(
                g.value.astype(self._dtype).astype(g.value.dtype))
        self._inner_opt.step()
