"""DGC (Deep Gradient Compression) momentum with error feedback.

Reference analog: fleet/meta_optimizers/dgc_optimizer.py +
paddle/fluid/operators/dgc_op.* (top-k gradient sparsification, momentum
correction, error accumulation; Lin et al. 2017). Like the reference's
DGCMomentumOptimizer, this IS the momentum optimizer — the DGC velocity u
replaces the plain momentum accumulator (wrapping a second momentum stage
would apply momentum twice).

TPU-native note: DGC exists to compress the dp gradient *exchange*; under
single-controller SPMD the exchange is an XLA collective, so the transform
preserves the NUMERICAL semantics (momentum correction + top-k masking +
error feedback) with a dense masked tensor — sparsity is a wire format,
and the wire belongs to XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer

__all__ = ["DGCMomentumOptimizer"]


class DGCMomentumOptimizer(Optimizer):
    """``sparsity`` is the DROP ratio (0.999 → keep the top 0.1% of
    gradient entries by magnitude). Before ``rampup_begin_step`` no
    compression is applied; over the following ``rampup_step`` updates the
    sparsity ramps linearly from 0 to its target (reference rampup
    semantics, dgc_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 sparsity=0.999, rampup_begin_step=0, rampup_step=1,
                 use_nesterov=False, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._sparsity = float(sparsity)
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._count = 0
        self._e = {}  # error feedback (what masking dropped)

    @classmethod
    def from_momentum(cls, inner, sparsity=0.999, rampup_begin_step=0,
                      rampup_step=1):
        """Build from an existing Momentum optimizer's settings (the
        strategy path: dgc REPLACES the momentum optimizer)."""
        return cls(learning_rate=inner._lr,
                   momentum=getattr(inner, "_momentum", 0.9),
                   parameters=inner._parameter_list,
                   sparsity=sparsity, rampup_begin_step=rampup_begin_step,
                   rampup_step=rampup_step,
                   use_nesterov=getattr(inner, "_use_nesterov", False),
                   grad_clip=getattr(inner, "_grad_clip", None))

    def _cur_sparsity(self):
        past = self._count - self._rampup_begin_step
        if past <= 0:
            return 0.0
        frac = min(1.0, past / self._rampup_step)
        return self._sparsity * frac

    def step(self):
        self._count += 1
        super().step()

    # checkpoint/resume must restore the compression state: a resumed run
    # with _count=0 would restart the sparsity rampup and drop all banked
    # error feedback
    def state_dict(self):
        sd = super().state_dict()
        sd["__dgc__"] = {"count": self._count,
                         "e": {k: jnp.asarray(v)
                               for k, v in self._e.items()}}
        return sd

    def set_state_dict(self, state_dict):
        dgc = state_dict.get("__dgc__")
        if dgc is not None:
            self._count = int(dgc.get("count", 0))
            self._e = {k: jnp.asarray(v)
                       for k, v in dgc.get("e", {}).items()}
        super().set_state_dict(state_dict)

    @staticmethod
    def _threshold(c, sparsity):
        """|c| magnitude threshold for the keep mask. Large tensors use a
        strided sample (the reference DGC samples ~0.1-1% for the same
        reason: a full per-step sort dominates at embedding-table sizes)."""
        flat = jnp.abs(c).reshape(-1).astype(jnp.float32)
        if flat.size > 65536:
            stride = flat.size // 65536
            flat = flat[::stride]
        return jnp.quantile(flat, sparsity)

    def _update_param(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        u = self._acc("velocity", p)
        u = self._momentum * u + g32
        # Nesterov look-ahead applies identically with and without
        # compression — the update rule must not change mid-training when
        # the rampup crosses zero
        v = g32 + self._momentum * u if self._use_nesterov else u
        sparsity = self._cur_sparsity()
        if sparsity > 0.0:
            k = self._key(p)
            c = v + self._e.get(k, jnp.zeros_like(v))
            mask = (jnp.abs(c) >= self._threshold(c, sparsity)).astype(
                jnp.float32)
            self._e[k] = c * (1.0 - mask)
            u = u * (1.0 - mask)
            upd = c * mask
        else:
            upd = v
        self._set_acc("velocity", p, u)
        return (p.value.astype(jnp.float32) - lr * upd).astype(p.value.dtype)
