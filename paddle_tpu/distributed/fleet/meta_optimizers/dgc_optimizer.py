"""DGC (Deep Gradient Compression) momentum with error feedback.

Reference analog: fleet/meta_optimizers/dgc_optimizer.py +
paddle/fluid/operators/dgc_op.* (top-k gradient sparsification, momentum
correction, error accumulation; Lin et al. 2017). Like the reference's
DGCMomentumOptimizer, this IS the momentum optimizer — the DGC velocity u
replaces the plain momentum accumulator (wrapping a second momentum stage
would apply momentum twice).

TPU-native note: DGC exists to compress the dp gradient *exchange*; under
single-controller SPMD the exchange is an XLA collective, so the transform
preserves the NUMERICAL semantics (momentum correction + top-k masking +
error feedback) with a dense masked tensor — sparsity is a wire format,
and the wire belongs to XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer

__all__ = ["DGCMomentumOptimizer"]


class DGCMomentumOptimizer(Optimizer):
    """``sparsity`` is the DROP ratio (0.999 → keep the top 0.1% of
    gradient entries by magnitude). Before ``rampup_begin_step`` no
    compression is applied; over the following ``rampup_step`` updates the
    sparsity ramps linearly from 0 to its target (reference rampup
    semantics, dgc_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 sparsity=0.999, rampup_begin_step=0, rampup_step=1,
                 use_nesterov=False, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._sparsity = float(sparsity)
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._count = 0
        self._e = {}  # error feedback (what masking dropped)

    @classmethod
    def from_momentum(cls, inner, sparsity=0.999, rampup_begin_step=0,
                      rampup_step=1):
        """Build from an existing Momentum optimizer's settings (the
        strategy path: dgc REPLACES the momentum optimizer)."""
        return cls(learning_rate=inner._lr,
                   momentum=getattr(inner, "_momentum", 0.9),
                   parameters=inner._parameter_list,
                   sparsity=sparsity, rampup_begin_step=rampup_begin_step,
                   rampup_step=rampup_step,
                   use_nesterov=getattr(inner, "_use_nesterov", False),
                   grad_clip=getattr(inner, "_grad_clip", None))

    def _cur_sparsity(self):
        past = self._count - self._rampup_begin_step
        if past <= 0:
            return 0.0
        frac = min(1.0, past / self._rampup_step)
        return self._sparsity * frac

    def step(self):
        self._count += 1
        super().step()

    def _update_param(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        u = self._acc("velocity", p)
        u = self._momentum * u + g32
        sparsity = self._cur_sparsity()
        if sparsity > 0.0:
            k = self._key(p)
            c = u + self._e.get(k, jnp.zeros_like(u))
            thresh = jnp.quantile(jnp.abs(c).reshape(-1).astype(jnp.float32),
                                  sparsity)
            mask = (jnp.abs(c) >= thresh).astype(jnp.float32)
            self._e[k] = c * (1.0 - mask)
            u = u * (1.0 - mask)
            upd = c * mask
        else:
            upd = u
        self._set_acc("velocity", p, u)
        if self._use_nesterov and sparsity == 0.0:
            upd = g32 + self._momentum * u
        return (p.value.astype(jnp.float32) - lr * upd).astype(p.value.dtype)
