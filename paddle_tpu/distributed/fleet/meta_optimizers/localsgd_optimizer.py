"""LocalSGDOptimizer — k local updates, then average params over dp.

Reference analog: fleet/meta_optimizers/localsgd_optimizer.py (inserts
c_allreduce on the params every k_steps). TPU-native: the averaging is an
eager all_reduce over the data-parallel group (XLA collective / stacked
ranks), params divided by dp world size.
"""
from __future__ import annotations

from .base import MetaOptimizerWrapper

__all__ = ["LocalSGDOptimizer"]


class LocalSGDOptimizer(MetaOptimizerWrapper):
    def __init__(self, inner_optimizer, k_steps: int = 1,
                 begin_step: int = 1, hcg=None):
        super().__init__(inner_optimizer)
        self._k_steps = max(1, int(k_steps))
        self._begin_step = int(begin_step)
        self._count = 0
        self._hcg = hcg

    def _extra_state(self):
        return {"count": self._count}

    def _load_extra_state(self, state):
        self._count = int(state.get("count", 0))

    def _hybrid_spans_processes(self):
        if self._hcg is None:
            from ... import fleet

            try:
                self._hcg = fleet.get_hybrid_communicate_group()
            except Exception:
                return False
        h = self._hcg
        return (h.get_model_parallel_world_size() > 1
                or h.get_pipe_parallel_world_size() > 1
                or h.get_sharding_parallel_world_size() > 1)

    def step(self):
        self._inner_opt.step()
        self._count += 1
        if self._count < self._begin_step or self._count % self._k_steps:
            return
        import jax

        if jax.process_count() == 1:
            # single-controller SPMD: params are logically global arrays —
            # every "replica" already sees the same values, the dp average
            # is the identity. The sync only has content across processes.
            return
        if self._hybrid_spans_processes():
            # processes hold different mp/pp/sharding shards — a flat
            # all-process mean would average unrelated tensors together
            raise NotImplementedError(
                "multi-process localsgd is only supported for pure-dp "
                "meshes (mp/pp/sharding degree 1)")
        from jax.experimental import multihost_utils

        import jax.numpy as jnp

        for p in self._inner_opt._parameter_list or []:
            gathered = multihost_utils.process_allgather(p.value)
            p.set_value(jnp.mean(
                gathered.astype(jnp.float32), axis=0).astype(p.value.dtype))

