"""Shared delegation shell for wrapper-style meta-optimizers.

One forwarding surface for all wrappers (the reference's MetaOptimizerBase
plays the same role for static passes): step() is the wrapper's own hook,
minimize() routes through SELF.step (a bound inner minimize would silently
skip the wrapper), and state_dict carries the wrapper's auxiliary state
(merge banks, counters, error feedback) so checkpoint/resume replays the
same trajectory.
"""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["MetaOptimizerWrapper"]


class MetaOptimizerWrapper:
    _META_KEY = "__meta_optimizer__"

    def __init__(self, inner_optimizer):
        self._inner_opt = inner_optimizer

    # wrappers override step(); everything else forwards
    def step(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)

    # -- wrapper aux state (counters, banks) -------------------------------
    def _extra_state(self) -> Dict[str, Any]:
        return {}

    def _load_extra_state(self, state: Dict[str, Any]):
        pass

    def state_dict(self):
        sd = dict(self._inner_opt.state_dict())
        extra = self._extra_state()
        if extra:
            sd.setdefault(self._META_KEY, {})[type(self).__name__] = extra
        return sd

    def set_state_dict(self, state_dict):
        meta = state_dict.get(self._META_KEY, {})
        mine = meta.get(type(self).__name__)
        if mine is not None:
            self._load_extra_state(mine)
        self._inner_opt.set_state_dict(state_dict)

    set_dict = set_state_dict

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
