from .hybrid_parallel_optimizer import HybridParallelOptimizer

__all__ = ["HybridParallelOptimizer"]
