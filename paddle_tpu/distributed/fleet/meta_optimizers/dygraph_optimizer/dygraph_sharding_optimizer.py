"""Stage-1 sharding optimizer (reference: DygraphShardingOptimizer,
meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:29).

The reference greedily partitions params by size across sharding ranks
(:94), runs the inner optimizer on the local shard (:134) and broadcasts
updated params after step (:143). TPU-native: the partition is a placement
policy — optimizer states are placed sharded over the ``sharding`` mesh
axis (sharded_optimizer.shard_optimizer_states); the post-step broadcast is
the all-gather GSPMD inserts where updated params are consumed. The greedy
rank partition survives only as ``_partition_parameters`` for introspection
parity."""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from .....optimizer.optimizer import Optimizer
from ....sharding.sharded_optimizer import shard_optimizer_states
from ....topology import get_mesh

__all__ = ["DygraphShardingOptimizer"]


class DygraphShardingOptimizer:
    def __init__(self, optimizer, hcg=None, sharding_degree=None, **kw):
        if not isinstance(optimizer, Optimizer):
            raise TypeError("inner optimizer must be a paddle_tpu Optimizer")
        self._inner_opt = optimizer
        self._hcg = hcg
        mesh = get_mesh()
        self._sharding_degree = int(
            sharding_degree or mesh.shape.get("sharding", 1))
        shard_optimizer_states(optimizer, mesh)

    # reference :94 — greedy size-ordered partition, kept for parity/debug
    def _partition_parameters(self) -> Dict[int, List]:
        mapping = {i: [] for i in range(max(self._sharding_degree, 1))}
        sizes = {i: 0 for i in mapping}
        params = list(self._inner_opt._parameter_list or [])
        for p in sorted(params, key=lambda q: -int(np.prod(q.shape))):
            rank = min(sizes, key=sizes.get)
            mapping[rank].append(p)
            sizes[rank] += int(np.prod(p.shape))
        return mapping

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)

    def minimize(self, loss, *a, **kw):
        return self._inner_opt.minimize(loss, *a, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
