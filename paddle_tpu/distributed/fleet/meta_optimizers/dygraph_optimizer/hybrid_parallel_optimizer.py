"""HybridParallelOptimizer + HybridParallelClipGrad.

Reference: meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:238
(wraps the inner optimizer; swaps ClipGradByGlobalNorm for a clip that
allreduces the squared-norm partials over mp/pp/sharding groups before
scaling; syncs non-distributed params over the mp group after step).

TPU-native: gradients are logical GLOBAL arrays under single-controller
SPMD, so the global-norm reduction is already global — no partial-norm
allreduce is needed in auto context. In manual (shard_map) context the clip
psums partial norms over every live hybrid axis, mirroring the reference.
The wrapper therefore focuses on (a) the clip-policy swap, (b) delegation.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .....nn.clip import ClipGradByGlobalNorm
from ....communication.core import in_traced_context

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    """hybrid_parallel_optimizer.py:286-301 parity."""

    def __init__(self, clip, hcg):
        super().__init__(getattr(clip, "clip_norm", 1.0))
        self._clip = clip
        self._hcg = hcg

    def _global_norm_sq(self, params_grads):
        live = [a for a in ("mp", "pp", "sharding") if in_traced_context(a)]
        if not live:
            # auto/GSPMD context: grads are logical global arrays — the plain
            # global norm is already correct.
            return super()._global_norm_sq(params_grads)
        # manual context: psum ONLY the distributed-param partials (reference
        # splits dist/non-dist exactly this way to avoid double-counting
        # replicated params, hybrid_parallel_optimizer.py:286-301)
        dist_pg = [(p, g) for p, g in params_grads
                   if getattr(p, "is_distributed", False)]
        rep_pg = [(p, g) for p, g in params_grads
                  if not getattr(p, "is_distributed", False)]
        total = super()._global_norm_sq(rep_pg)
        if dist_pg:
            part = super()._global_norm_sq(dist_pg)
            for axis in live:
                part = lax.psum(part, axis)
            total = total + part
        return total


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # walk wrapper meta-optimizers (gradient-merge, fp16-allreduce, …)
        # to the raw Optimizer: its step() reads its OWN _grad_clip, so the
        # swap must land there, not on a delegating wrapper
        raw = optimizer
        while hasattr(raw, "_inner_opt"):
            raw = raw._inner_opt
        clip = getattr(raw, "_grad_clip", None)
        if isinstance(clip, ClipGradByGlobalNorm) and not isinstance(
                clip, HybridParallelClipGrad):
            raw._grad_clip = HybridParallelClipGrad(clip, hcg)
        # sharding stage-1: shard optimizer states over the sharding axis
        sharding_degree = (hcg.get_sharding_parallel_world_size()
                           if hcg is not None else 1)
        if sharding_degree > 1:
            from ....sharding.sharded_optimizer import shard_optimizer_states

            shard_optimizer_states(optimizer)

    # -- delegation --------------------------------------------------------
    def step(self):
        return self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def clear_grad(self, set_to_zero: bool = False):
        return self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
