"""Meta-optimizers: strategy-driven optimizer transforms.

Reference analog: fleet/meta_optimizers/ — static-graph passes that rewrite
the program per DistributedStrategy flag. TPU-native: the same transforms
wrap the eager optimizer (fleet.distributed_optimizer composes them from
the strategy), and the compiled path gets the equivalent semantics from
jit-level machinery (grad accumulation in the train step, bf16 arrays on
the wire).
"""
from . import dygraph_optimizer
from .dgc_optimizer import DGCMomentumOptimizer
from .fp16_allreduce_optimizer import FP16AllReduceOptimizer
from .gradient_merge_optimizer import GradientMergeOptimizer
from .lars_optimizer import LarsMomentumOptimizer
from .localsgd_optimizer import LocalSGDOptimizer

__all__ = ["dygraph_optimizer", "GradientMergeOptimizer",
           "LocalSGDOptimizer", "DGCMomentumOptimizer",
           "LarsMomentumOptimizer", "FP16AllReduceOptimizer"]
