"""GradientMergeOptimizer — accumulate k steps of gradients, update once.

Reference analog: fleet/meta_optimizers/gradient_merge_optimizer.py (the
static-graph pass rewrites the program with gradient-merge vars + a cond;
here the same semantics wrap the eager optimizer: fp32 accumulation
buffers, an update every ``k_steps``-th call, optional averaging).
"""
from __future__ import annotations

import jax.numpy as jnp

from .base import MetaOptimizerWrapper

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer(MetaOptimizerWrapper):
    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        super().__init__(inner_optimizer)
        self._k_steps = max(1, int(k_steps))
        self._avg = avg
        self._count = 0
        self._acc = {}  # param key -> fp32 accumulator

    @property
    def inner_opt(self):
        return self._inner_opt

    def _key(self, p):
        return self._inner_opt._key(p)

    def _extra_state(self):
        return {"count": self._count,
                "acc": {k: jnp.asarray(v) for k, v in self._acc.items()}}

    def _load_extra_state(self, state):
        self._count = int(state.get("count", 0))
        self._acc = {k: jnp.asarray(v)
                     for k, v in state.get("acc", {}).items()}

    def step(self):
        self._count += 1
        do_update = self._count % self._k_steps == 0
        pgs = self._inner_opt._collect_params_grads()
        for p, g in pgs:
            if g is None:
                continue
            k = self._key(p)
            a = self._acc.get(k)
            g32 = g.value.astype(jnp.float32)
            self._acc[k] = g32 if a is None else a + g32
        if not do_update:
            # swallow this step: grads are banked, inner never sees them
            self._inner_opt.clear_grad()
            return
        from ....core.tensor import Tensor

        scale = 1.0 / self._k_steps if self._avg else 1.0
        for p, g in pgs:
            k = self._key(p)
            if k in self._acc:
                p.grad = Tensor((self._acc[k] * scale).astype(p.value.dtype))
        self._acc.clear()
        self._inner_opt.step()
