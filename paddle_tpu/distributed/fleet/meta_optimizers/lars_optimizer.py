"""LARS momentum (You et al. 2017) — layerwise trust-ratio LR scaling.

Reference analog: fleet/meta_optimizers/lars_optimizer.py +
python/paddle/fluid optimizer LarsMomentumOptimizer (lars_op kernel):
local_lr = lr * coeff * ||w|| / (||g|| + lambda*||w||), then momentum.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer

__all__ = ["LarsMomentumOptimizer"]


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9, name=None,
                 exclude_from_weight_decay=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _update_param(self, p, g, lr):
        g32 = g.astype(jnp.float32)
        w32 = p.value.astype(jnp.float32)
        wd = self._lars_wd
        if any(tok in (p.name or "") for tok in self._exclude):
            wd = 0.0
        w_norm = jnp.sqrt(jnp.sum(w32 * w32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + wd * w_norm
                                         + self._epsilon),
            1.0)
        local_lr = lr * trust
        v = self._acc("velocity", p)
        v_new = self._momentum * v + local_lr * (g32 + wd * w32)
        self._set_acc("velocity", p, v_new)
        return (w32 - v_new).astype(p.value.dtype)
