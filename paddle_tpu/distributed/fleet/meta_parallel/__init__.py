from ..layers.mpu import (ColumnParallelLinear, ParallelCrossEntropy,
                          RowParallelLinear, VocabParallelEmbedding,
                          get_rng_state_tracker, model_parallel_random_seed)
from .tensor_parallel import TensorParallel

__all__ = ["ColumnParallelLinear", "ParallelCrossEntropy",
           "RowParallelLinear", "VocabParallelEmbedding",
           "get_rng_state_tracker", "model_parallel_random_seed",
           "TensorParallel"]


def __getattr__(name):
    # lazily resolve PP/sharding symbols added by later milestones
    if name in ("PipelineLayer", "LayerDesc", "SharedLayerDesc",
                "PipelineParallel", "PipelineParallelWithInterleave"):
        from . import pp_layers, pipeline_parallel

        mod = pp_layers if "Layer" in name and "Parallel" not in name else pipeline_parallel
        return getattr(mod, name)
    if name == "ShardingParallel":
        from .sharding_parallel import ShardingParallel

        return ShardingParallel
    if name in ("GroupShardedOptimizerStage2", "GroupShardedStage2",
                "GroupShardedStage3"):
        from . import sharding

        return getattr(sharding, name)
    raise AttributeError(name)
