"""Pipeline-parallel schedule driver.

Reference: meta_parallel/pipeline_parallel.py — ``PipelineParallel``
(:124), ``forward_backward_pipeline`` 1F1B (:372, startup/steady :399-480),
``train_batch`` (:572), ``PipelineParallelWithInterleave`` (:804).

TPU-native redesign: the reference schedules NCCL p2p send/recv between
per-stage processes; activation transfer and schedule order are the
program. Under single-controller SPMD there are two execution paths:

- **eager** (this module): one logical executor owns every stage, so the
  1F1B dependency order collapses to "forward+backward per microbatch,
  immediately" — which has the same arithmetic as 1F1B (grad accumulation
  over microbatches) and strictly better peak activation memory (1 live
  graph vs pipeline-depth graphs). This is the semantics/parity path the
  reference tests check (PP loss == serial loss).
- **compiled** (pp_compiled.py): the performance path — microbatches
  stream through mesh-sharded stages via ``ppermute`` inside one jitted
  program; XLA overlaps the ICI transfer with compute. That is where the
  pipeline bubble/memory trade-off of 1F1B lives on TPU.
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ....core.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


def _split_leading(x, n):
    """Split array/Tensor into n microbatches along axis 0."""
    if isinstance(x, (tuple, list)):
        parts = [_split_leading(v, n) for v in x]
        return [type(x)(p[i] for p in parts) for i in range(n)]
    val = x._value if isinstance(x, Tensor) else x
    if val.shape[0] % n != 0:
        raise ValueError(
            f"batch dim {val.shape[0]} not divisible by accumulate_steps {n}")
    m = val.shape[0] // n
    return [Tensor(val[i * m:(i + 1) * m]) if isinstance(x, Tensor)
            else val[i * m:(i + 1) * m] for i in range(n)]


class PipelineParallel(MetaParallelBase):
    """reference pipeline_parallel.py:124 parity."""

    def __init__(self, layers, hcg=None, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        super().__init__(layers, hcg, strategy)
        cfg = {}
        if strategy is not None:
            cfg = dict(getattr(strategy, "pipeline_configs", {}) or {})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self.num_stages = layers.num_stages
        self.total_loss = None
        self._compiled_step = None

    def _prepare_for_model(self):
        # place params per their pspecs on the live mesh (no-op single device)
        from ..._spmd import shard_params
        from ...topology import get_mesh

        shard_params(self._layers, get_mesh())

    # -- schedule -----------------------------------------------------------
    def forward_backward_pipeline(self, data, scaler=None, compute_grad=True):
        """Run all microbatches forward (+backward); returns mean loss.

        1F1B arithmetic: grads accumulate across microbatches with loss
        scaled by 1/accumulate_steps (reference scales in _backward_step).
        """
        inputs, labels = data
        micro_x = _split_leading(inputs, self.accumulate_steps)
        micro_y = _split_leading(labels, self.accumulate_steps)

        total = None
        for x, y in zip(micro_x, micro_y):
            out = self._layers(x)
            if self._layers._loss_fn is None:
                raise ValueError("PipelineLayer needs loss_fn for train_batch")
            loss = self._layers._loss_fn(out, y)
            if loss.ndim > 0:
                loss = loss.mean()
            loss = loss / self.accumulate_steps
            if compute_grad:
                seed = scaler.scale(loss) if scaler is not None else loss
                seed.backward()
            loss = loss.detach()
            total = loss if total is None else total + loss
        self.total_loss = total
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference pipeline_parallel.py:572."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ....core.autograd import no_grad

        with no_grad():
            if compute_loss:
                return self.forward_backward_pipeline(data, compute_grad=False)
            inputs, _ = data if isinstance(data, tuple) else (data, None)
            return self._layers(inputs)

    # -- compiled fast path -------------------------------------------------
    def compiled_train_step(self, mesh=None, **kw):
        """Build (lazily) the jitted ppermute pipeline step over the pp mesh
        axis — see pp_compiled.build_pipeline_train_step."""
        if self._compiled_step is None:
            from .pp_compiled import build_pipeline_train_step

            self._compiled_step = build_pipeline_train_step(
                self._layers, accumulate_steps=self.accumulate_steps,
                mesh=mesh, **kw)
        return self._compiled_step


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-stage (interleaved 1F1B) variant, reference
    pipeline_parallel.py:804. Eagerly the chunk order is the model order;
    the interleave schedule matters only for the compiled path, where chunks
    round-robin over stages to cut the bubble (micro-step → chunk mapping ≙
    reference _get_virtual_pp_rank :890)."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = layers.get_num_virtual_stages()
        if self.num_model_chunks < 2:
            raise ValueError(
                "PipelineParallelWithInterleave requires "
                "num_virtual_pipeline_stages >= 2")

    def _get_virtual_pp_rank(self, micro_step, forward=True):
        """Chunk index a stage works on at `micro_step` (reference :890)."""
        group = self.num_stages * self.num_model_chunks
        pos = micro_step % group
        chunk = pos // self.num_stages
        if not forward:
            chunk = self.num_model_chunks - chunk - 1
        return chunk
