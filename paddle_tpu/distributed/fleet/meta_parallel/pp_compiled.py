"""Compiled pipeline parallelism: microbatches streamed through mesh-sharded
stages with ``ppermute`` inside ONE jitted program.

This is the TPU-native replacement for the reference's NCCL p2p schedule
(meta_parallel/pp_utils/p2p_communication.py: SendRecvMeta :47, _p2p_helper
:302 building batch_isend_irecv): instead of per-rank processes exchanging
tensors, the whole 1F1B wavefront is a ``lax.scan`` over schedule ticks run
under ``shard_map`` on the ``pp`` mesh axis. Each tick every stage computes
its microbatch and ``ppermute``s the activation to the next stage over ICI;
XLA overlaps the transfer with the next tick's compute. The backward
pipeline comes for free: the transpose of ``ppermute`` is the reverse
``ppermute``, so ``jax.grad`` of this function IS the backward schedule.

Stage dispatch is a ``lax.switch`` over per-stage functions, so stages may
be heterogeneous (embedding stage / decoder stages / head+loss stage).
Parameters are passed replicated into the shard_map (each branch only reads
its own stage's subtree; shard_map's transpose psums the cotangents, which
is exactly the cross-stage grad reduction). A ZeRO-style sharded-param
variant composes by sharding the param pytree on the ``sharding`` axis
outside this function.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ....nn.functional_call import substituted_state
from ...topology import get_mesh

__all__ = ["build_pipeline_loss_fn", "build_pipeline_train_step"]


def _to_val(x):
    return x._value if isinstance(x, Tensor) else x


def _stage_caller(pipe, stage_idx):
    """Pure fn: (params_dict, x) -> stage output, running the stage's layers
    eagerly under trace via substituted_state (the functional_call pattern)."""
    idxs = pipe.stage_layer_indices(stage_idx)
    return _layers_caller(pipe, idxs)


def _chunk_caller(pipe, chunk):
    """Pure fn for ONE virtual-stage chunk (global chunk index = virtual
    position p; chunk p lives on device p % num_stages)."""
    return _layers_caller(pipe, pipe.chunk_layer_indices(chunk))


def _layers_caller(pipe, idxs):
    def run(params, x):
        from ....core.autograd import no_grad

        with substituted_state(pipe, params), no_grad():
            t = x if isinstance(x, Tensor) else Tensor(x)
            for i in idxs:
                t = pipe.run_function[i](t)
        return _to_val(t)

    return run


def build_pipeline_loss_fn(pipe, accumulate_steps: int,
                           mesh: Optional[Mesh] = None,
                           remat: bool = False) -> Callable:
    """Returns ``loss_fn(params, inputs, labels) -> mean_loss`` where the
    microbatch wavefront is pipelined over the mesh's ``pp`` axis.

    params: dict name->array (full model, as layer.named_parameters()).
    inputs/labels: global batch; leading dim split into `accumulate_steps`
    microbatches.
    """
    if pipe._loss_fn is None:
        raise ValueError("PipelineLayer needs loss_fn for the pipeline step")
    mesh = mesh or get_mesh()
    S = int(mesh.shape.get("pp", 1))
    M = int(accumulate_steps)
    V = int(pipe.get_num_virtual_stages())
    loss_fn = pipe._loss_fn
    if S > 1 and S != pipe.num_stages:
        raise ValueError(
            f"mesh pp axis has {S} devices but PipelineLayer was segmented "
            f"into {pipe.num_stages} stages — rebuild one of them")
    if S > 1 and V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs accumulate_steps ({M}) divisible "
            f"by the number of stages ({S})")

    def serial_loss(params, inputs, labels):
        # S==1 (no/absent pp axis): run every chunk serially — the model is
        # the composition of all virtual stages. Scanned so grad-accum
        # memory matches the pipelined path.
        n_chunks = pipe.total_chunks
        fns = [_chunk_caller(pipe, c) for c in range(n_chunks)]

        def micro(carry, xy):
            x, y = xy
            h = x
            for c in range(n_chunks):
                h = fns[c](params, h)
            l = _to_val(loss_fn(Tensor(h), Tensor(y)))
            return carry + jnp.mean(l), None

        xs = jnp.reshape(inputs, (M, inputs.shape[0] // M) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, labels.shape[0] // M) + labels.shape[1:])
        total, _ = lax.scan(micro, jnp.zeros((), jnp.float32), (xs, ys))
        return total / M

    if S == 1:
        return serial_loss

    # Virtual stage p = k·S + s (chunk k of device s); micro-step i runs at
    # tick t = i + s with k = (i mod L)//S, m = (i//L)·S + (i mod S). The
    # modular ring ppermute delivers device S-1's chunk-k output to device
    # 0's chunk-k+1 exactly one tick before consumption (see the 1F1B
    # docstring for the algebra); V == 1 degenerates to the classic
    # wavefront with m = i.
    L = S * V
    NF = M * V
    chunk_fns = [_chunk_caller(pipe, p) for p in range(L)]

    def pipelined_loss(params, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])
        h_aval = jax.eval_shape(
            lambda p, x: chunk_fns[0](p, x), params,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))

        def worker(params, xs, ys):
            s = lax.axis_index("pp")
            perm = [(i, (i + 1) % S) for i in range(S)]

            def branch(p):
                fn = chunk_fns[p]
                first = p == 0
                last = p == L - 1

                def go(x_raw, h_recv, y_t):
                    out = fn(params, x_raw if first else h_recv)
                    if last:
                        l = _to_val(loss_fn(Tensor(out), Tensor(y_t)))
                        return (jnp.zeros(h_aval.shape, h_aval.dtype),
                                jnp.mean(l).astype(jnp.float32))
                    return out.astype(h_aval.dtype), jnp.zeros((), jnp.float32)

                return go if not remat else jax.checkpoint(go)

            branches = [branch(p) for p in range(L)]

            def tick(carry, t):
                h_recv, acc = carry
                i = t - s
                valid = jnp.logical_and(i >= 0, i < NF)
                ic = jnp.clip(i, 0, NF - 1)
                p = ((ic % L) // S) * S + s
                m = (ic // L) * S + ic % S
                h_out, l = lax.switch(p, branches, xs[m], h_recv, ys[m])
                acc = acc + jnp.where(valid, l, 0.0)
                h_next = lax.ppermute(h_out, "pp", perm)
                return (h_next, acc), None

            carry0 = (jnp.zeros(h_aval.shape, h_aval.dtype),
                      jnp.zeros((), jnp.float32))
            (_, acc), _ = lax.scan(tick, carry0, jnp.arange(NF + S - 1))
            # only the last stage accumulated loss; psum broadcasts it
            return lax.psum(acc, "pp")

        from jax import shard_map

        # manual ONLY over pp: other mesh axes (mp/dp/sharding) stay "auto",
        # so GSPMD still honors the TP sharding constraints inside stages
        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            axis_names={"pp"},
            check_vma=False)
        return fn(params, xs, ys) / M

    return pipelined_loss


def build_pipeline_1f1b_grad_fn(pipe, accumulate_steps: int,
                                mesh: Optional[Mesh] = None) -> Callable:
    """Returns ``grad_fn(params, inputs, labels) -> (loss, grads)`` running a
    TRUE 1F1B schedule — with interleaved virtual stages when the
    PipelineLayer was built with ``num_virtual_pipeline_stages > 1``.

    Reference: 1F1B steady state at pipeline_parallel.py:430-480; interleave
    at :804 with the micro-step→chunk mapping of ``_get_virtual_pp_rank``
    (:890). Unlike :func:`build_pipeline_loss_fn` (whose ``jax.grad``
    transpose replays ALL forward ticks before any backward — the GPipe
    memory profile, activations for all M microbatches live at the peak),
    this schedule interleaves one backward per forward tick and keeps only a
    stash of stage-INPUT activations bounded by the pipeline depth
    (independent of M); stage interiors are rematerialised by per-tick
    ``jax.vjp`` (~1.33x ideal FLOPs — the full-recompute choice).

    This is the GENERIC builder: heterogeneous stages, replicated params.
    The scale path is ``pp_sharded.build_sharded_1f1b_resid_grad_fn``
    (stage-LOCAL params + residual stashing, ~1.001x ideal FLOPs): use it
    for homogeneous-body LLMs where the double-forward matters.

    Schedule algebra (V chunks per device, L = S·V virtual stages, chunk k
    of device s is virtual stage p = k·S + s):
    - forward micro-step i runs at tick t = i + s with chunk
      k = (i mod L)//S and microbatch m = (i//L)·S + (i mod S); the
      ``ppermute`` ring (i → i+1 mod S) delivers each activation exactly one
      tick before its consumer reaches it (device S-1's chunk-k output IS
      device 0's chunk-k+1 input) — no deep buffering.
    - backward micro-step j runs at tick t = j + L + S − 2 − s with chunk
      k_b = V−1−(j mod L)//S, mirrored over the reverse ring; its cotangent
      seed for the last virtual stage comes from the loss VJP in the same
      tick, so backward ticks start the moment microbatch 0 finishes.
    """
    if pipe._loss_fn is None:
        raise ValueError("PipelineLayer needs loss_fn for the pipeline step")
    mesh = mesh or get_mesh()
    S = int(mesh.shape.get("pp", 1))
    M = int(accumulate_steps)
    V = int(pipe.get_num_virtual_stages())
    loss_fn = pipe._loss_fn

    if S == 1:
        serial = build_pipeline_loss_fn(pipe, M, mesh)
        return jax.value_and_grad(serial)

    if S != pipe.num_stages:
        raise ValueError(
            f"mesh pp axis has {S} devices but PipelineLayer was segmented "
            f"into {pipe.num_stages} stages — rebuild one of them")
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs accumulate_steps ({M}) divisible "
            f"by the number of stages ({S})")
    L = S * V
    NF = M * V                # fwd micro-steps per device
    G = 2 * S + 4             # stash slots per chunk (≥ max in-flight ≈ 2S)
    chunk_fns = [_chunk_caller(pipe, p) for p in range(L)]

    def grad_fn(params, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])
        h_aval = jax.eval_shape(
            lambda p, x: chunk_fns[0](p, x), params,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))
        # Per-microbatch RNG base: the forward lax.switch trace and the
        # backward jax.vjp re-trace each run under trace_key_scope(fold_in
        # (base, m)), so trace-time draws (F.dropout, flash-attn seeds) land
        # on IDENTICAL keys for the same microbatch — without this the remat
        # would apply different dropout masks in forward and backward.
        # (base is concrete at trace time: under a jitted train step masks
        # repeat across steps; the grads stay exactly consistent with the
        # loss either way.)
        from ....core.random import default_generator

        base_key = default_generator.next_key()

        def worker(params, xs, ys):
            s = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            T = NF + L + S - 2

            def branch(p):
                fn = chunk_fns[p]
                first = p == 0
                last = p == L - 1

                def go(prm, x_raw, h_in, y):
                    out = fn(prm, x_raw if first else h_in)
                    if last:
                        l = jnp.mean(_to_val(loss_fn(Tensor(out), Tensor(y))))
                        return (jnp.zeros(h_aval.shape, h_aval.dtype),
                                l.astype(jnp.float32))
                    return out.astype(h_aval.dtype), jnp.zeros((), jnp.float32)

                return go

            branches = [branch(p) for p in range(L)]

            def tick(carry, t):
                h_recv, g_recv, stash, grads, lacc = carry
                # ---------- forward part ----------
                from ....core.random import trace_key_scope

                i = t - s
                fvalid = jnp.logical_and(i >= 0, i < NF)
                ic = jnp.clip(i, 0, NF - 1)
                k = (ic % L) // S
                p = k * S + s
                m = (ic // L) * S + ic % S
                with trace_key_scope(jax.random.fold_in(base_key, m)):
                    h_out, _ = lax.switch(p, branches, params, xs[m],
                                          h_recv, ys[m])
                # stash this micro-step's INPUT for its backward remat (the
                # p==0 branch reads xs directly, so its slot is dead weight)
                stash = lax.cond(
                    fvalid,
                    lambda st: st.at[k, m % G].set(
                        h_recv.astype(h_aval.dtype)),
                    lambda st: st, stash)

                # ---------- backward part ----------
                j = t - (L + S - 2 - s)
                bvalid = jnp.logical_and(j >= 0, j < NF)
                jc = jnp.clip(j, 0, NF - 1)
                kb = V - 1 - (jc % L) // S
                pb = kb * S + s
                m_b = (jc // L) * S + jc % S
                x_b = stash[kb, m_b % G]

                def f(prm, h_in):
                    with trace_key_scope(jax.random.fold_in(base_key, m_b)):
                        return lax.switch(pb, branches, prm, xs[m_b], h_in,
                                          ys[m_b])

                (_, l_b), vjp = jax.vjp(f, params, x_b)
                bmask = bvalid.astype(jnp.float32)
                seed = (g_recv * bmask.astype(h_aval.dtype), bmask)
                gp, gx = vjp(seed)          # linear in seed → zero when invalid
                grads2 = jax.tree.map(jnp.add, grads, gp)
                lacc = lacc + jnp.where(bvalid, l_b, 0.0)

                h_next = lax.ppermute(h_out, "pp", fwd_perm)
                g_next = lax.ppermute(gx, "pp", bwd_perm)
                return (h_next, g_next, stash, grads2, lacc), None

            carry0 = (
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros((V, G) + h_aval.shape, h_aval.dtype),
                jax.tree.map(lambda x: jnp.zeros(x.shape, x.dtype), params),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, grads, lacc), _ = lax.scan(tick, carry0, jnp.arange(T))
            # loss lives on the last device; per-stage param grads are zero
            # elsewhere — psum assembles both (replicated-param contract)
            grads = jax.tree.map(lambda g: lax.psum(g, "pp"), grads)
            return lax.psum(lacc, "pp") / M, jax.tree.map(
                lambda g: g / M, grads)

        from jax import shard_map

        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=(P(), P()),
            axis_names={"pp"},
            check_vma=False)
        return fn(params, xs, ys)

    return grad_fn


def build_pipeline_train_step(pipe, accumulate_steps: int,
                              mesh: Optional[Mesh] = None,
                              lr: float = 1e-3,
                              optimizer: str = "adamw",
                              remat: bool = False,
                              donate: bool = True,
                              schedule: str = "1f1b"):
    """Full jitted PP train step: pipelined forward + backward + optimizer
    update. ``schedule``:

    - ``"1f1b"`` (default): true one-forward-one-backward interleaving —
      live activations bounded by pipeline depth, not microbatch count;
      supports interleaved virtual stages.
    - ``"gpipe"``: forward wavefront then ``jax.grad`` transpose (simpler
      program; all-microbatch activation live range, tame with ``remat``).

    Returns ``(step, init)``:

    - ``init(params) -> opt_state``
    - ``step(params, opt_state, inputs, labels) -> (params, opt_state, loss)``
    """
    from ....optimizer.functional import adamw_init, adamw_update, sgd_update

    if schedule == "1f1b":
        grad_fn = build_pipeline_1f1b_grad_fn(pipe, accumulate_steps, mesh)
    elif schedule == "gpipe":
        loss_fn = build_pipeline_loss_fn(pipe, accumulate_steps, mesh, remat)
        grad_fn = jax.value_and_grad(loss_fn)
    else:
        raise ValueError(f"unknown schedule {schedule!r}")

    def init(params):
        if optimizer == "adamw":
            return adamw_init(params)
        return ()

    def step(params, opt_state, inputs, labels):
        loss, grads = grad_fn(params, inputs, labels)
        if optimizer == "adamw":
            opt_state, params = adamw_update(grads, opt_state, params, lr=lr)
        else:
            params = sgd_update(grads, params, lr=lr)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums), init
