"""Compiled pipeline parallelism: microbatches streamed through mesh-sharded
stages with ``ppermute`` inside ONE jitted program.

This is the TPU-native replacement for the reference's NCCL p2p schedule
(meta_parallel/pp_utils/p2p_communication.py: SendRecvMeta :47, _p2p_helper
:302 building batch_isend_irecv): instead of per-rank processes exchanging
tensors, the whole 1F1B wavefront is a ``lax.scan`` over schedule ticks run
under ``shard_map`` on the ``pp`` mesh axis. Each tick every stage computes
its microbatch and ``ppermute``s the activation to the next stage over ICI;
XLA overlaps the transfer with the next tick's compute. The backward
pipeline comes for free: the transpose of ``ppermute`` is the reverse
``ppermute``, so ``jax.grad`` of this function IS the backward schedule.

Stage dispatch is a ``lax.switch`` over per-stage functions, so stages may
be heterogeneous (embedding stage / decoder stages / head+loss stage).
Parameters are passed replicated into the shard_map (each branch only reads
its own stage's subtree; shard_map's transpose psums the cotangents, which
is exactly the cross-stage grad reduction). A ZeRO-style sharded-param
variant composes by sharding the param pytree on the ``sharding`` axis
outside this function.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ....nn.functional_call import substituted_state
from ...topology import get_mesh

__all__ = ["build_pipeline_loss_fn", "build_pipeline_train_step"]


def _to_val(x):
    return x._value if isinstance(x, Tensor) else x


def _stage_caller(pipe, stage_idx):
    """Pure fn: (params_dict, x) -> stage output, running the stage's layers
    eagerly under trace via substituted_state (the functional_call pattern)."""
    idxs = pipe.stage_layer_indices(stage_idx)

    def run(params, x):
        from ....core.autograd import no_grad

        with substituted_state(pipe, params), no_grad():
            t = x if isinstance(x, Tensor) else Tensor(x)
            for i in idxs:
                t = pipe.run_function[i](t)
        return _to_val(t)

    return run


def build_pipeline_loss_fn(pipe, accumulate_steps: int,
                           mesh: Optional[Mesh] = None,
                           remat: bool = False) -> Callable:
    """Returns ``loss_fn(params, inputs, labels) -> mean_loss`` where the
    microbatch wavefront is pipelined over the mesh's ``pp`` axis.

    params: dict name->array (full model, as layer.named_parameters()).
    inputs/labels: global batch; leading dim split into `accumulate_steps`
    microbatches.
    """
    if pipe._loss_fn is None:
        raise ValueError("PipelineLayer needs loss_fn for the pipeline step")
    if pipe.get_num_virtual_stages() > 1:
        # interleaved virtual chunks need a chunk-hopping schedule (stage s
        # runs chunk c, activations revisit stages); _stage_caller's
        # contiguous per-stage composition would compute the WRONG function
        raise NotImplementedError(
            "compiled pipeline does not support interleaved virtual stages "
            "yet — use num_virtual_pipeline_stages=1 or the eager schedule")
    mesh = mesh or get_mesh()
    S = int(mesh.shape.get("pp", 1))
    M = int(accumulate_steps)
    loss_fn = pipe._loss_fn
    if S > 1 and S != pipe.num_stages:
        raise ValueError(
            f"mesh pp axis has {S} devices but PipelineLayer was segmented "
            f"into {pipe.num_stages} stages — rebuild one of them")
    # S==1 (no/absent pp axis): run ALL segmented stages serially, not just
    # stage 0 — the model is the composition of every stage
    n_exec = pipe.num_stages if S == 1 else S
    stage_fns = [_stage_caller(pipe, s) for s in range(n_exec)]

    def serial_loss(params, inputs, labels):
        # S==1 or no pp axis: plain microbatch accumulation (still scanned
        # so grad-accum memory matches the pipelined path)
        def micro(carry, xy):
            x, y = xy
            h = x
            for s in range(n_exec):
                h = stage_fns[s](params, h)
            l = _to_val(loss_fn(Tensor(h), Tensor(y)))
            return carry + jnp.mean(l), None

        xs = jnp.reshape(inputs, (M, inputs.shape[0] // M) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, labels.shape[0] // M) + labels.shape[1:])
        total, _ = lax.scan(micro, jnp.zeros((), jnp.float32), (xs, ys))
        return total / M

    if S == 1:
        return serial_loss

    def pipelined_loss(params, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])

        # static activation shape: output aval of stage 0 on one microbatch
        h_aval = jax.eval_shape(
            lambda p, x: stage_fns[0](p, x), params,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))

        def worker(params, xs, ys):
            s = lax.axis_index("pp")
            T = M + S - 1  # wavefront ticks
            perm = [(i, i + 1) for i in range(S - 1)]

            def branch(b):
                fn = stage_fns[b]
                is_last = b == S - 1

                def go(x_in, h_recv, y_t):
                    inp = x_in if b == 0 else h_recv
                    out = fn(params, inp)
                    if is_last:
                        l = _to_val(loss_fn(Tensor(out), Tensor(y_t)))
                        return jnp.zeros(h_aval.shape, h_aval.dtype), jnp.mean(l).astype(jnp.float32)
                    return out.astype(h_aval.dtype), jnp.zeros((), jnp.float32)

                return go if not remat else jax.checkpoint(go)

            branches = [branch(b) for b in range(S)]

            def tick(carry, t):
                h_recv, acc = carry
                # stage s works on microbatch m = t - s when 0 <= m < M
                m = t - s
                valid = jnp.logical_and(m >= 0, m < M)
                mi = jnp.clip(m, 0, M - 1)
                x_t = xs[mi]
                y_t = ys[mi]
                h_out, l = lax.switch(s, branches, x_t, h_recv, y_t)
                acc = acc + jnp.where(valid, l, 0.0)
                h_next = lax.ppermute(h_out, "pp", perm)
                return (h_next, acc), None

            carry0 = (jnp.zeros(h_aval.shape, h_aval.dtype),
                      jnp.zeros((), jnp.float32))
            (_, acc), _ = lax.scan(tick, carry0, jnp.arange(M + S - 1))
            # only the last stage accumulated loss; psum broadcasts it
            return lax.psum(acc, "pp")

        from jax import shard_map

        # manual ONLY over pp: other mesh axes (mp/dp/sharding) stay "auto",
        # so GSPMD still honors the TP sharding constraints inside stages
        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            axis_names={"pp"},
            check_vma=False)
        return fn(params, xs, ys) / M

    return pipelined_loss


def build_pipeline_train_step(pipe, accumulate_steps: int,
                              mesh: Optional[Mesh] = None,
                              lr: float = 1e-3,
                              optimizer: str = "adamw",
                              remat: bool = False,
                              donate: bool = True):
    """Full jitted PP train step: pipelined forward, backward (the reverse
    wavefront, via grad-of-ppermute), optimizer update. Returns
    ``(step, init)``:

    - ``init(params) -> opt_state``
    - ``step(params, opt_state, inputs, labels) -> (params, opt_state, loss)``
    """
    from ....optimizer.functional import adamw_init, adamw_update, sgd_update

    loss_fn = build_pipeline_loss_fn(pipe, accumulate_steps, mesh, remat)
    grad_fn = jax.value_and_grad(loss_fn)

    def init(params):
        if optimizer == "adamw":
            return adamw_init(params)
        return ()

    def step(params, opt_state, inputs, labels):
        loss, grads = grad_fn(params, inputs, labels)
        if optimizer == "adamw":
            opt_state, params = adamw_update(grads, opt_state, params, lr=lr)
        else:
            params = sgd_update(grads, params, lr=lr)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums), init
