"""Pipeline-parallel layer description & segmentation.

Reference: meta_parallel/parallel_layers/pp_layers.py — ``LayerDesc`` (:56),
``SharedLayerDesc`` (:76, tied embeddings), ``SegmentLayers`` (:92, uniform /
param-count / manual segmentation), ``PipelineLayer`` (:239, interleave
segmentation :417-430).

TPU-native redesign: the reference instantiates ONLY the local stage's layers
in each process and wires NCCL p2p between ranks. Single-controller SPMD
instead builds the FULL model once; every parameter is tagged with its stage
id (``param.pp_stage``) so (a) the eager 1F1B driver knows the stage
boundaries, and (b) the compiled pipeline (pp_compiled.py) can stack
homogeneous stages and shard them over the ``pp`` mesh axis. Running the
PipelineLayer eagerly is bit-identical to the serial model — the reference's
PP-vs-serial loss-parity test contract (SURVEY.md §4.2,
hybrid_parallel_pp_transformer.py).
"""
from __future__ import annotations

import math
import re
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Deferred layer constructor (reference pp_layers.py:56)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("The input of LayerDesc must be Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared between stages (tied input/output
    embeddings). Reference pp_layers.py:76: each process in the shared-comm
    group holds a replica and allreduces the grads; here sharing is literal —
    one Layer object appears at every use site, so the autograd engine
    accumulates both contributions into the same ``.grad`` and no comm is
    needed (the TPU-native collapse of ``allreduce_shared_weight_gradients``).
    """

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layer descs into num_parts contiguous stages.
    Reference pp_layers.py:92. Methods: "uniform", "layer:<Name>" (split at
    layers of the named class, e.g. "layer:TransformerBlock")."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 num_virtual_pipeline_stage=None):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        if num_virtual_pipeline_stage:
            self.total_parts = num_parts * num_virtual_pipeline_stage
        else:
            self.total_parts = num_parts
        if self.num_items < self.total_parts:
            raise ValueError("layer number should be greater than number of "
                             "segments")

    def do_segment(self) -> List[int]:
        if isinstance(self.method, list):
            # manual boundaries: num_parts+1 monotonically increasing indices
            seg = self.method
            if seg[0] != 0 or seg[-1] != self.num_items or len(seg) != self.total_parts + 1:
                raise ValueError(f"invalid manual segment {seg}")
            return list(seg)
        if self.method == "uniform":
            return self.uniform(self.num_items, self.total_parts)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            weights = [0] * len(self._layers_desc)
            for i, d in enumerate(self._layers_desc):
                cls = d.layer_func if isinstance(d, LayerDesc) else type(d)
                if getattr(cls, "__name__", "") == name:
                    weights[i] = 1
            actual = sum(weights)
            if actual < self.total_parts:
                raise ValueError(
                    f"need at least {self.total_parts} layers named {name}, "
                    f"found {actual}")
            return self.segment_by_weights(weights)
        if self.method == "parameter":
            weights = []
            for d in self._layers_desc:
                # estimate param count without building: build once, count,
                # discard (descs are cheap relative to training)
                layer = d.build_layer() if isinstance(d, LayerDesc) else d
                n = sum(int(np.prod(p.shape)) for _, p in layer.named_parameters())
                weights.append(max(n, 1))
            return self.segment_by_weights(weights)
        raise ValueError(f"unknown seg_method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result

    def segment_by_weights(self, weights) -> List[int]:
        """Balance cumulative weight across parts: boundary k sits where the
        prefix sum first reaches k/parts of the total, clamped so every part
        keeps >= 1 layer and enough layers remain for the parts after it."""
        n = self.num_items
        parts = self.total_parts
        cum = [0.0]
        for w in weights:
            cum.append(cum[-1] + w)
        total = cum[-1]
        result = [0]
        for part in range(1, parts):
            target = total * part / parts
            j = result[-1] + 1             # part gets at least one layer
            hi = n - (parts - part)        # leave >=1 layer per later part
            while j < hi and cum[j] < target:
                j += 1
            result.append(min(max(j, result[-1] + 1), hi))
        result.append(n)
        return result


class PipelineLayer(Layer):
    """The PP model container (reference pp_layers.py:239).

    Accepts a list of ``LayerDesc``/``Layer``/callables; builds the full
    model; segments it into ``num_stages`` (× virtual chunks); tags each
    parameter with ``pp_stage``. ``forward`` runs the whole model (optionally
    rematerialising every ``recompute_interval`` layers), which is the serial
    parity baseline AND the single-chip path.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        if num_stages is None and topology is None:
            from ...topology import axis_size

            num_stages = max(axis_size("pp"), 1)
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = int(num_stages or 1)
        self._topo = topology
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = num_virtual_pipeline_stages or 1
        if self._num_virtual_pipeline_stages > 1:
            if not isinstance(seg_method, list) and not str(seg_method).startswith("layer:") and seg_method != "uniform":
                raise ValueError(
                    "interleave requires uniform/layer/manual segmentation")

        self._layers_desc = list(layers)
        self.shared_layers: dict = {}

        built: List[Layer] = []
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self.shared_layers:
                    self.shared_layers[d.layer_name] = d.build_layer()
                layer = self.shared_layers[d.layer_name]
                if d.forward_func is not None:
                    layer = _SharedForward(layer, d.forward_func)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        self.run_function = LayerList(built)

        # segment over the BUILT layers (not descs): the "parameter" method
        # counts params from the live objects instead of constructing every
        # layer a second time
        seg = SegmentLayers(
            built, self._num_stages, seg_method,
            num_virtual_pipeline_stage=(self._num_virtual_pipeline_stages
                                        if self._num_virtual_pipeline_stages > 1
                                        else None))
        self.segment_parts = seg.do_segment()
        # chunk c (total_parts chunks) → stage c % num_stages under interleave
        # (reference pp_layers.py:417-430 assigns chunks round-robin)
        self._chunk_of_layer = [0] * len(built)
        for c in range(len(self.segment_parts) - 1):
            for i in range(self.segment_parts[c], self.segment_parts[c + 1]):
                self._chunk_of_layer[i] = c
        for i, layer in enumerate(built):
            stage = self._chunk_of_layer[i] % self._num_stages
            for _, p in layer.named_parameters():
                p.pp_stage = stage

    # -- introspection ------------------------------------------------------
    @property
    def num_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        return self._num_virtual_pipeline_stages

    def get_stage_from_index(self, index) -> int:
        return self._chunk_of_layer[index] % self._num_stages

    def stage_layer_indices(self, stage_id, chunk_id=None) -> List[int]:
        """Indices of layers on `stage_id` (optionally one virtual chunk)."""
        out = []
        for i, c in enumerate(self._chunk_of_layer):
            if c % self._num_stages != stage_id:
                continue
            if chunk_id is not None and c // self._num_stages != chunk_id:
                continue
            out.append(i)
        return out

    def chunk_layer_indices(self, chunk) -> List[int]:
        return [i for i, c in enumerate(self._chunk_of_layer) if c == chunk]

    @property
    def total_chunks(self) -> int:
        return len(self.segment_parts) - 1

    def forward_chunk(self, x, chunk):
        for i in self.chunk_layer_indices(chunk):
            x = self.run_function[i](x)
        return x

    # -- serial forward (parity baseline / single chip) ---------------------
    def forward(self, input):
        x = input
        if self._recompute_interval <= 0:
            for layer in self.run_function:
                x = layer(x)
            return x
        from ..recompute.recompute import recompute

        layers = list(self.run_function)
        from ....nn.layer.container import Sequential

        i = 0
        while i < len(layers):
            j = min(i + self._recompute_interval, len(layers))
            seg = layers[i:j]

            # remat every full segment; a SHORT tail segment (a lone
            # embedding/head when interval > 1) keeps its activation — a
            # one-layer activation is cheap and rerunning it buys nothing.
            # interval == 1 means the user asked for per-layer remat: honor it.
            if j - i > 1 or self._recompute_interval == 1:
                # a Sequential VIEW (not a closure) so recompute() threads
                # the segment's parameters through the autograd tape —
                # closure-captured weights are remat constants and would get
                # no grad under eager backward()
                x = recompute(Sequential(*seg), x)
            else:
                for l in seg:
                    x = l(x)
            i = j
        return x

    def allreduce_shared_weight_gradients(self):
        """reference pp_layers.py shared-weight grad sync — structural no-op:
        shared layers are one object, grads already accumulated together."""
        return None


class _FuncLayer(Layer):
    """Wrap a plain callable (e.g. a lambda reshaping activations) as a Layer
    so pipelines may mix functions and Layers, as the reference allows."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)


class _SharedForward(Layer):
    """A use-site of a SharedLayerDesc with a custom forward_func (e.g. the
    output-projection use of a tied embedding)."""

    def __init__(self, shared: Layer, forward_func: Callable):
        super().__init__()
        self.shared = shared
        self._forward_func = forward_func

    def forward(self, x):
        return self._forward_func(self.shared, x)
