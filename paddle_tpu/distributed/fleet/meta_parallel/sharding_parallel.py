"""ShardingParallel model wrapper (reference meta_parallel/sharding_parallel.py:22).

The reference broadcasts params inside the sharding group at wrap time so
ranks agree; single-controller SPMD has one logical copy, so the wrapper's
job is placement: put every param on the mesh per its PartitionSpec."""
from __future__ import annotations

from .meta_parallel_base import MetaParallelBase


class ShardingParallel(MetaParallelBase):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__(layers, hcg, strategy)

    def _prepare_for_model(self):
        from ..._spmd import shard_params
        from ...topology import get_mesh

        shard_params(self._layers, get_mesh())
