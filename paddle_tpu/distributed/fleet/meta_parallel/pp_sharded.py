"""Stage-LOCAL parameter pipeline parallelism (compiled 1F1B).

`pp_compiled.py` passes the full param pytree replicated across the ``pp``
axis — correct, but every device then holds params+grads for the whole
model, so PP there scales only activation memory. The reference's
PipelineLayer instead gives each stage ONLY its own layers
(meta_parallel/parallel_layers/pp_layers.py:239 — per-stage param
ownership; SegmentLayers:92): that partitioning is why PP exists at 65B.

This module is the TPU-native equivalent for homogeneous-body pipelines
(the shape every LLM has): per-layer params are STACKED into leading-dim
arrays — ``blocks`` with leading dims ``(S, V, lpc, ...)`` where ``S`` =
pipeline stages, ``V`` = virtual chunks per stage, ``lpc`` = layers per
chunk — and sharded ``P("pp")`` on dim 0. Under ``shard_map`` (manual over
``pp`` only) each device materializes exactly its own ``(V, lpc, ...)``
slice; the 1F1B grad carry is that same local shape, so params, grads AND
optimizer state are all 1/S per device. Small non-repeating "edge" params
(embedding, final norm, lm head) ride along replicated; their cotangents
are psum'd (they are O(vocab·h), not O(L·h²)).

Schedule algebra is identical to ``pp_compiled.build_pipeline_1f1b_grad_fn``
(see its docstring): virtual stage p = k·S + s, forward micro-step i at
tick t = i + s, backward j at t = j + L + S − 2 − s, modular ``ppermute``
rings. Because every branch indexes the LOCAL chunk k = p//S statically,
device s only ever touches chunks it owns.

TP / DP / ZeRO compose through GSPMD: ``mp``/``dp``/``sharding`` mesh axes
stay *auto* inside the shard_map, so NamedSharding annotations on the
feature dims of ``blocks`` (Megatron column/row splits), on the microbatch
dim of the inputs (dp), and on the optimizer moments (ZeRO placement)
propagate and XLA inserts the collectives. See
``models/llama_pp.build_llama_hybrid_step`` for the composed 4-axis step.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ...topology import get_mesh

__all__ = ["build_sharded_1f1b_grad_fn", "build_sharded_1f1b_resid_grad_fn",
           "blocks_from_stacked", "stacked_from_blocks"]


def blocks_from_stacked(stacked, S: int, V: int = 1):
    """Rearrange a layer-stacked pytree (leading dim = n_layers, layer order)
    into pp blocks with leading dims (S, V, lpc): block[s, k] holds the
    layers of virtual stage p = k·S + s (chunk k of device s), i.e. global
    layers [p·lpc, (p+1)·lpc).  lpc = n_layers // (S·V)."""

    def go(x):
        L = x.shape[0]
        if L % (S * V):
            raise ValueError(
                f"{L} layers not divisible into {S} stages x {V} chunks")
        lpc = L // (S * V)
        # (p, lpc, ...) with p = k*S + s  ->  index [s, k] = chunk k*S+s
        y = x.reshape((V, S, lpc) + x.shape[1:])   # [k, s, lpc, ...]
        return jnp.swapaxes(y, 0, 1)               # [s, k, lpc, ...]

    return jax.tree.map(go, stacked)


def stacked_from_blocks(blocks):
    """Inverse of :func:`blocks_from_stacked` (for checkpoint interop)."""

    def go(x):
        S, V, lpc = x.shape[:3]
        y = jnp.swapaxes(x, 0, 1)                  # [k, s, lpc, ...]
        return y.reshape((S * V * lpc,) + x.shape[3:])

    return jax.tree.map(go, blocks)


def _psum_f32(tree, axis):
    """Cross-stage grad reduction in fp32. Two reasons: (1) summing S
    bf16 partials in fp32 is numerically tighter (the mix-precision
    main-grad convention); (2) XLA CPU's AllReducePromotion pass crashes
    cloning a LOW-precision all-reduce emitted by a partially-manual
    shard_map (bf16 psum over the manual 'pp' axis while mp/sharding are
    auto ->  reduction computation contains a 'copy' opcode; reproduced
    jax 0.9.0) — fp32 psums never enter that pass, keeping the compile-
    only 13B/65B memory analysis runnable on virtual CPU meshes."""
    return jax.tree.map(
        lambda g: lax.psum(g.astype(jnp.float32), axis).astype(g.dtype),
        tree)


def _schedule_dims(mesh, accumulate_steps, num_virtual_stages):
    """Shared 1F1B schedule constants for both builders: (S stages, M
    microbatches, V chunks/device, L virtual stages, NF fwd micro-steps,
    G stash slots). Keep the two builders' schedule algebra identical —
    edit here, not in one of them.

    G = 2S is the TIGHT stash bound (it directly scales residual-stash
    HBM): a slot written at forward tick t_f is read at its backward tick
    t_b with t_b − t_f = (V−1−2k)·S + L+S−2−2s ≤ 2L−2, and the next
    write to the same (chunk, m % G) slot comes (G/S)·L ticks later —
    G = 2S (a multiple of S) gives 2L > 2L−2. Wraparound is exercised by
    the M ≫ G parity test (tests/test_pp_resid.py)."""
    S = int(mesh.shape.get("pp", 1))
    M = int(accumulate_steps)
    V = int(num_virtual_stages)
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs accumulate_steps ({M}) divisible "
            f"by the number of stages ({S})")
    return S, M, V, S * V, M * V, 2 * S


def build_sharded_1f1b_resid_grad_fn(
        first_fn: Callable[[Any, Any], Any],
        body_fwd: Callable[[Any, Any], Any],
        body_bwd: Callable[[Any, Any, Any], Any],
        last_fn: Callable[[Any, Any, Any], Any],
        accumulate_steps: int,
        mesh: Optional[Mesh] = None,
        num_virtual_stages: int = 1) -> Callable:
    """Residual-stashing 1F1B: the double-forward eliminator.

    :func:`build_sharded_1f1b_grad_fn` stashes only each chunk's INPUT and
    re-runs the chunk forward inside ``jax.vjp`` at the backward tick —
    ~33% extra FLOPs (forward runs twice per microbatch). This variant
    takes the chunk as an explicit fwd/bwd PAIR:

    - ``body_fwd(chunk, h) -> (h_out, res)`` — residuals are plain arrays;
    - ``body_bwd(chunk, res, g) -> (g_chunk, g_h)`` — MUST be linear in
      ``g`` (invalid-tick masking seeds a zero cotangent) and take the
      chunk params explicitly (no weight copies ride the stash).

    The schedule stashes ``res`` between a microbatch's forward and
    backward ticks — exactly the reference's stored-activation 1F1B
    (meta_parallel/pipeline_parallel.py:372 holds forward outputs until
    _backward_step :677) — so each DECODER forward runs ONCE. The edges
    still go through per-tick ``jax.vjp``: ``last_fn`` (norm+head+loss)
    runs once total (its forward only executes at the backward tick),
    while ``first_fn`` runs twice (forward tick + vjp re-run) — fine for
    an embedding lookup, so keep ``first_fn`` cheap. Total FLOPs come out
    ~ideal fwd+bwd (measured 1.001x per device);
    tests/test_pp_resid.py asserts the compiled-HLO bound.

    Memory: the stash holds ``G = 2S`` slots of FULL per-chunk residuals
    (vs one boundary activation) — the same activation footprint the
    reference's stored-activation 1F1B pays. At scales where that exceeds
    HBM, use the input-stashing builder (its vjp re-run is then the remat
    choice, like the reference's recompute integration).

    Build the pair for Llama with ``models.llama_residual.make_body_fwd_bwd``;
    grad parity vs the serial model is asserted in tests/test_pp_resid.py.
    """
    mesh = mesh or get_mesh()
    S, M, V, L, NF, G = _schedule_dims(mesh, accumulate_steps,
                                       num_virtual_stages)

    if S == 1:
        # serial: same composition; the chunk's AD rule IS the hand-split
        # pair (custom_vjp), so the body backward never re-traces the
        # forward — and never tries to differentiate through a raw
        # pallas_call inside body_fwd
        @jax.custom_vjp
        def chunk_apply(chunk, h):
            return body_fwd(chunk, h)[0]

        def _ca_fwd(chunk, h):
            y, res = body_fwd(chunk, h)
            return y, (chunk, res)

        def _ca_bwd(saved, g):
            chunk, res = saved
            return body_bwd(chunk, res, g)

        chunk_apply.defvjp(_ca_fwd, _ca_bwd)

        def loss_all(blocks, edge, inputs, labels):
            mb = inputs.shape[0] // M
            xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
            ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])

            def micro(acc, xy):
                x, y = xy
                h = first_fn(edge, x)
                for p in range(L):
                    h = chunk_apply(
                        jax.tree.map(lambda b: b[0, p // S], blocks), h)
                return acc + last_fn(edge, h, y), None

            tot, _ = lax.scan(micro, jnp.zeros((), jnp.float32), (xs, ys))
            return tot / M

        vg = jax.value_and_grad(loss_all, argnums=(0, 1))
        return lambda b, e, i, y: vg(b, e, i, y)

    from ....core.random import default_generator, trace_key_scope

    def grad_fn(blocks, edge, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])
        h_aval = jax.eval_shape(
            lambda e, x: first_fn(e, x), edge,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))
        # residual aval: structure is chunk-independent (homogeneous body)
        chunk_aval = jax.tree.map(
            lambda b: jax.ShapeDtypeStruct(b.shape[2:], b.dtype), blocks)
        res_aval = jax.eval_shape(
            lambda c, h: body_fwd(c, h)[1], chunk_aval, h_aval)
        base_key = default_generator.next_key()

        def worker(blocks, edge, xs, ys):
            blocks = jax.tree.map(lambda b: b[0], blocks)   # local (V, lpc,…)
            s = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            T = NF + L + S - 2

            def fbranch(p):
                k, first, last = p // S, p == 0, p == L - 1

                def go(local, edge, x_raw, h_in):
                    chunk = jax.tree.map(lambda b: b[k], local)
                    h0 = first_fn(edge, x_raw) if first else h_in
                    h, res = body_fwd(chunk, h0)
                    # ring value; the last chunk's body OUTPUT is stashed
                    # separately for the backward tick's last_fn vjp
                    ring = (jnp.zeros(h_aval.shape, h_aval.dtype) if last
                            else h.astype(h_aval.dtype))
                    h_last = (h.astype(h_aval.dtype) if last
                              else jnp.zeros(h_aval.shape, h_aval.dtype))
                    return ring, res, h_last

                return go

            def bbranch(pb):
                kb, first, last = pb // S, pb == 0, pb == L - 1

                def go(local, edge, res, g_recv, h_last, x_raw, y, bmask):
                    chunk = jax.tree.map(lambda b: b[kb], local)
                    if last:
                        l_b, vjp_l = jax.vjp(
                            lambda e, h: last_fn(e, h, y), edge, h_last)
                        ge, g_h = vjp_l(bmask)
                        g_h = g_h.astype(h_aval.dtype)
                    else:
                        l_b = jnp.zeros((), jnp.float32)
                        ge = jax.tree.map(
                            lambda e: jnp.zeros(e.shape, e.dtype), edge)
                        g_h = g_recv * bmask.astype(h_aval.dtype)
                    g_chunk, g_h_in = body_bwd(chunk, res, g_h)
                    if first:
                        _, vjp_f = jax.vjp(
                            lambda e: first_fn(e, x_raw), edge)
                        (ge_f,) = vjp_f(g_h_in)
                        ge = jax.tree.map(jnp.add, ge, ge_f)
                        g_out = jnp.zeros(h_aval.shape, h_aval.dtype)
                    else:
                        g_out = g_h_in.astype(h_aval.dtype)
                    return g_out, g_chunk, ge, l_b.astype(jnp.float32)

                return go

            fbranches = [fbranch(p) for p in range(L)]
            bbranches = [bbranch(p) for p in range(L)]

            def tick(carry, t):
                (h_recv, g_recv, stash_res, stash_hl, bgrads, egrads,
                 lacc) = carry
                # ---- forward ----
                i = t - s
                fvalid = jnp.logical_and(i >= 0, i < NF)
                ic = jnp.clip(i, 0, NF - 1)
                k = (ic % L) // S
                p = k * S + s
                m = (ic // L) * S + ic % S
                with trace_key_scope(jax.random.fold_in(base_key, m)):
                    h_out, res, h_last = lax.switch(
                        p, fbranches, blocks, edge, xs[m], h_recv)
                stash_res = lax.cond(
                    fvalid,
                    lambda st: jax.tree.map(
                        lambda sl, r: sl.at[k, m % G].set(r), st, res),
                    lambda st: st, stash_res)
                stash_hl = lax.cond(
                    jnp.logical_and(fvalid, p == L - 1),
                    lambda st: st.at[m % G].set(h_last),
                    lambda st: st, stash_hl)

                # ---- backward ----
                j = t - (L + S - 2 - s)
                bvalid = jnp.logical_and(j >= 0, j < NF)
                jc = jnp.clip(j, 0, NF - 1)
                kb = V - 1 - (jc % L) // S
                pb = kb * S + s
                m_b = (jc // L) * S + jc % S
                res_b = jax.tree.map(lambda sl: sl[kb, m_b % G], stash_res)
                bmask = bvalid.astype(jnp.float32)
                with trace_key_scope(jax.random.fold_in(base_key, m_b)):
                    g_out, g_chunk, ge, l_b = lax.switch(
                        pb, bbranches, blocks, edge, res_b, g_recv,
                        stash_hl[m_b % G], xs[m_b], ys[m_b], bmask)
                bgrads = jax.tree.map(
                    lambda bg, gc: bg.at[kb].add(gc), bgrads, g_chunk)
                egrads = jax.tree.map(jnp.add, egrads, ge)
                lacc = lacc + jnp.where(bvalid, l_b, 0.0)

                h_next = lax.ppermute(h_out, "pp", fwd_perm)
                g_next = lax.ppermute(g_out, "pp", bwd_perm)
                return (h_next, g_next, stash_res, stash_hl, bgrads,
                        egrads, lacc), None

            carry0 = (
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jax.tree.map(lambda r: jnp.zeros((V, G) + r.shape, r.dtype),
                             res_aval),
                jnp.zeros((G,) + h_aval.shape, h_aval.dtype),
                jax.tree.map(lambda b: jnp.zeros(b.shape, b.dtype), blocks),
                jax.tree.map(lambda e: jnp.zeros(e.shape, e.dtype), edge),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, _, bgrads, egrads, lacc), _ = lax.scan(
                tick, carry0, jnp.arange(T))
            bgrads = jax.tree.map(lambda g: g[None] / M, bgrads)
            egrads = jax.tree.map(lambda g: g / M, _psum_f32(egrads, "pp"))
            return lax.psum(lacc, "pp") / M, bgrads, egrads

        from jax import shard_map

        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P("pp"), P()),
            axis_names={"pp"},
            check_vma=False)
        loss, bgrads, egrads = fn(blocks, edge, xs, ys)
        return loss, (bgrads, egrads)

    return grad_fn


def build_sharded_1f1b_grad_fn(
        first_fn: Callable[[Any, Any], Any],
        body_fn: Callable[[Any, Any], Any],
        last_fn: Callable[[Any, Any, Any], Any],
        accumulate_steps: int,
        mesh: Optional[Mesh] = None,
        num_virtual_stages: int = 1) -> Callable:
    """Returns ``grad_fn(blocks, edge, inputs, labels) ->
    (loss, (block_grads, edge_grads))`` running TRUE 1F1B with stage-local
    parameters.

    - ``first_fn(edge, x_raw) -> h``: the stage-0 prefix (embedding).
    - ``body_fn(chunk, h) -> h``: one chunk of ``lpc`` homogeneous layers;
      ``chunk`` is the pytree slice with leading dim ``lpc``.
    - ``last_fn(edge, h, labels_mb) -> scalar loss`` (final norm + head +
      loss), mean over the microbatch.
    - ``blocks``: pytree, every leaf leading dims ``(S, V, lpc, ...)``; pass
      it in sharded ``P("pp")`` (dim 0) for stage-local placement.
    - ``edge``: small replicated pytree consumed by first/last.

    The returned ``block_grads`` keeps the (S, V, lpc, ...) layout sharded
    over pp — feed it straight to a functional optimizer whose state carries
    the same sharding and the whole update stays 1/S per device.
    """
    mesh = mesh or get_mesh()
    S, M, V, L, NF, G = _schedule_dims(mesh, accumulate_steps,
                                       num_virtual_stages)

    if S == 1:
        # no pp axis: serial chunks with scanned grad accumulation
        def loss_all(blocks, edge, inputs, labels):
            mb = inputs.shape[0] // M
            xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
            ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])

            def micro(acc, xy):
                x, y = xy
                h = first_fn(edge, x)
                for p in range(L):
                    h = body_fn(jax.tree.map(lambda b: b[0, p // S], blocks),
                                h)
                return acc + last_fn(edge, h, y), None

            tot, _ = lax.scan(micro, jnp.zeros((), jnp.float32), (xs, ys))
            return tot / M

        vg = jax.value_and_grad(loss_all, argnums=(0, 1))
        return lambda b, e, i, y: vg(b, e, i, y)

    from ....core.random import default_generator, trace_key_scope

    def grad_fn(blocks, edge, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])
        # activation aval at a stage boundary (post-embedding shape)
        h_aval = jax.eval_shape(
            lambda e, x: first_fn(e, x), edge,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))
        base_key = default_generator.next_key()

        def worker(blocks, edge, xs, ys):
            # local view: (1, V, lpc, ...) -> (V, lpc, ...)
            blocks = jax.tree.map(lambda b: b[0], blocks)
            s = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            T = NF + L + S - 2

            def branch(p):
                k, first, last = p // S, p == 0, p == L - 1

                def go(local, edge, x_raw, h_in, y):
                    chunk = jax.tree.map(lambda b: b[k], local)
                    h = body_fn(chunk, first_fn(edge, x_raw) if first
                                else h_in)
                    if last:
                        l = last_fn(edge, h, y)
                        return (jnp.zeros(h_aval.shape, h_aval.dtype),
                                l.astype(jnp.float32))
                    return (h.astype(h_aval.dtype),
                            jnp.zeros((), jnp.float32))

                return go

            branches = [branch(p) for p in range(L)]

            def tick(carry, t):
                h_recv, g_recv, stash, bgrads, egrads, lacc = carry
                # ---- forward ----
                i = t - s
                fvalid = jnp.logical_and(i >= 0, i < NF)
                ic = jnp.clip(i, 0, NF - 1)
                k = (ic % L) // S
                p = k * S + s
                m = (ic // L) * S + ic % S
                with trace_key_scope(jax.random.fold_in(base_key, m)):
                    h_out, _ = lax.switch(p, branches, blocks, edge,
                                          xs[m], h_recv, ys[m])
                stash = lax.cond(
                    fvalid,
                    lambda st: st.at[k, m % G].set(
                        h_recv.astype(h_aval.dtype)),
                    lambda st: st, stash)

                # ---- backward ----
                j = t - (L + S - 2 - s)
                bvalid = jnp.logical_and(j >= 0, j < NF)
                jc = jnp.clip(j, 0, NF - 1)
                kb = V - 1 - (jc % L) // S
                pb = kb * S + s
                m_b = (jc // L) * S + jc % S
                x_b = stash[kb, m_b % G]

                def f(local, edge, h_in):
                    with trace_key_scope(jax.random.fold_in(base_key, m_b)):
                        return lax.switch(pb, branches, local, edge,
                                          xs[m_b], h_in, ys[m_b])

                (_, l_b), vjp = jax.vjp(f, blocks, edge, x_b)
                bmask = bvalid.astype(jnp.float32)
                seed = (g_recv * bmask.astype(h_aval.dtype), bmask)
                gl, ge, gx = vjp(seed)
                bgrads = jax.tree.map(jnp.add, bgrads, gl)
                egrads = jax.tree.map(jnp.add, egrads, ge)
                lacc = lacc + jnp.where(bvalid, l_b, 0.0)

                h_next = lax.ppermute(h_out, "pp", fwd_perm)
                g_next = lax.ppermute(gx, "pp", bwd_perm)
                return (h_next, g_next, stash, bgrads, egrads, lacc), None

            carry0 = (
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros((V, G) + h_aval.shape, h_aval.dtype),
                jax.tree.map(lambda b: jnp.zeros(b.shape, b.dtype), blocks),
                jax.tree.map(lambda e: jnp.zeros(e.shape, e.dtype), edge),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, bgrads, egrads, lacc), _ = lax.scan(
                tick, carry0, jnp.arange(T))
            # block grads are STAGE-LOCAL: just restore the sharded leading
            # dim — no cross-stage psum (this is the memory win)
            bgrads = jax.tree.map(lambda g: g[None] / M, bgrads)
            # edge grads & loss are replicated-contract: psum assembles
            egrads = jax.tree.map(lambda g: g / M, _psum_f32(egrads, "pp"))
            return lax.psum(lacc, "pp") / M, bgrads, egrads

        from jax import shard_map

        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P("pp"), P()),
            axis_names={"pp"},
            check_vma=False)
        loss, bgrads, egrads = fn(blocks, edge, xs, ys)
        return loss, (bgrads, egrads)

    return grad_fn
