"""Stage-LOCAL parameter pipeline parallelism (compiled 1F1B).

`pp_compiled.py` passes the full param pytree replicated across the ``pp``
axis — correct, but every device then holds params+grads for the whole
model, so PP there scales only activation memory. The reference's
PipelineLayer instead gives each stage ONLY its own layers
(meta_parallel/parallel_layers/pp_layers.py:239 — per-stage param
ownership; SegmentLayers:92): that partitioning is why PP exists at 65B.

This module is the TPU-native equivalent for homogeneous-body pipelines
(the shape every LLM has): per-layer params are STACKED into leading-dim
arrays — ``blocks`` with leading dims ``(S, V, lpc, ...)`` where ``S`` =
pipeline stages, ``V`` = virtual chunks per stage, ``lpc`` = layers per
chunk — and sharded ``P("pp")`` on dim 0. Under ``shard_map`` (manual over
``pp`` only) each device materializes exactly its own ``(V, lpc, ...)``
slice; the 1F1B grad carry is that same local shape, so params, grads AND
optimizer state are all 1/S per device. Small non-repeating "edge" params
(embedding, final norm, lm head) ride along replicated; their cotangents
are psum'd (they are O(vocab·h), not O(L·h²)).

Schedule algebra is identical to ``pp_compiled.build_pipeline_1f1b_grad_fn``
(see its docstring): virtual stage p = k·S + s, forward micro-step i at
tick t = i + s, backward j at t = j + L + S − 2 − s, modular ``ppermute``
rings. Because every branch indexes the LOCAL chunk k = p//S statically,
device s only ever touches chunks it owns.

TP / DP / ZeRO compose through GSPMD: ``mp``/``dp``/``sharding`` mesh axes
stay *auto* inside the shard_map, so NamedSharding annotations on the
feature dims of ``blocks`` (Megatron column/row splits), on the microbatch
dim of the inputs (dp), and on the optimizer moments (ZeRO placement)
propagate and XLA inserts the collectives. See
``models/llama_pp.build_llama_hybrid_step`` for the composed 4-axis step.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ...topology import get_mesh

__all__ = ["build_sharded_1f1b_grad_fn", "blocks_from_stacked",
           "stacked_from_blocks"]


def blocks_from_stacked(stacked, S: int, V: int = 1):
    """Rearrange a layer-stacked pytree (leading dim = n_layers, layer order)
    into pp blocks with leading dims (S, V, lpc): block[s, k] holds the
    layers of virtual stage p = k·S + s (chunk k of device s), i.e. global
    layers [p·lpc, (p+1)·lpc).  lpc = n_layers // (S·V)."""

    def go(x):
        L = x.shape[0]
        if L % (S * V):
            raise ValueError(
                f"{L} layers not divisible into {S} stages x {V} chunks")
        lpc = L // (S * V)
        # (p, lpc, ...) with p = k*S + s  ->  index [s, k] = chunk k*S+s
        y = x.reshape((V, S, lpc) + x.shape[1:])   # [k, s, lpc, ...]
        return jnp.swapaxes(y, 0, 1)               # [s, k, lpc, ...]

    return jax.tree.map(go, stacked)


def stacked_from_blocks(blocks):
    """Inverse of :func:`blocks_from_stacked` (for checkpoint interop)."""

    def go(x):
        S, V, lpc = x.shape[:3]
        y = jnp.swapaxes(x, 0, 1)                  # [k, s, lpc, ...]
        return y.reshape((S * V * lpc,) + x.shape[3:])

    return jax.tree.map(go, blocks)


def build_sharded_1f1b_grad_fn(
        first_fn: Callable[[Any, Any], Any],
        body_fn: Callable[[Any, Any], Any],
        last_fn: Callable[[Any, Any, Any], Any],
        accumulate_steps: int,
        mesh: Optional[Mesh] = None,
        num_virtual_stages: int = 1) -> Callable:
    """Returns ``grad_fn(blocks, edge, inputs, labels) ->
    (loss, (block_grads, edge_grads))`` running TRUE 1F1B with stage-local
    parameters.

    - ``first_fn(edge, x_raw) -> h``: the stage-0 prefix (embedding).
    - ``body_fn(chunk, h) -> h``: one chunk of ``lpc`` homogeneous layers;
      ``chunk`` is the pytree slice with leading dim ``lpc``.
    - ``last_fn(edge, h, labels_mb) -> scalar loss`` (final norm + head +
      loss), mean over the microbatch.
    - ``blocks``: pytree, every leaf leading dims ``(S, V, lpc, ...)``; pass
      it in sharded ``P("pp")`` (dim 0) for stage-local placement.
    - ``edge``: small replicated pytree consumed by first/last.

    The returned ``block_grads`` keeps the (S, V, lpc, ...) layout sharded
    over pp — feed it straight to a functional optimizer whose state carries
    the same sharding and the whole update stays 1/S per device.
    """
    mesh = mesh or get_mesh()
    S = int(mesh.shape.get("pp", 1))
    M = int(accumulate_steps)
    V = int(num_virtual_stages)
    L = S * V
    NF = M * V
    G = 2 * S + 4

    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs accumulate_steps ({M}) divisible "
            f"by the number of stages ({S})")

    if S == 1:
        # no pp axis: serial chunks with scanned grad accumulation
        def loss_all(blocks, edge, inputs, labels):
            mb = inputs.shape[0] // M
            xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
            ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])

            def micro(acc, xy):
                x, y = xy
                h = first_fn(edge, x)
                for p in range(L):
                    h = body_fn(jax.tree.map(lambda b: b[0, p // S], blocks),
                                h)
                return acc + last_fn(edge, h, y), None

            tot, _ = lax.scan(micro, jnp.zeros((), jnp.float32), (xs, ys))
            return tot / M

        vg = jax.value_and_grad(loss_all, argnums=(0, 1))
        return lambda b, e, i, y: vg(b, e, i, y)

    from ....core.random import default_generator, trace_key_scope

    def grad_fn(blocks, edge, inputs, labels):
        mb = inputs.shape[0] // M
        xs = jnp.reshape(inputs, (M, mb) + inputs.shape[1:])
        ys = jnp.reshape(labels, (M, mb) + labels.shape[1:])
        # activation aval at a stage boundary (post-embedding shape)
        h_aval = jax.eval_shape(
            lambda e, x: first_fn(e, x), edge,
            jax.ShapeDtypeStruct((mb,) + inputs.shape[1:], inputs.dtype))
        base_key = default_generator.next_key()

        def worker(blocks, edge, xs, ys):
            # local view: (1, V, lpc, ...) -> (V, lpc, ...)
            blocks = jax.tree.map(lambda b: b[0], blocks)
            s = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            T = NF + L + S - 2

            def branch(p):
                k, first, last = p // S, p == 0, p == L - 1

                def go(local, edge, x_raw, h_in, y):
                    chunk = jax.tree.map(lambda b: b[k], local)
                    h = body_fn(chunk, first_fn(edge, x_raw) if first
                                else h_in)
                    if last:
                        l = last_fn(edge, h, y)
                        return (jnp.zeros(h_aval.shape, h_aval.dtype),
                                l.astype(jnp.float32))
                    return (h.astype(h_aval.dtype),
                            jnp.zeros((), jnp.float32))

                return go

            branches = [branch(p) for p in range(L)]

            def tick(carry, t):
                h_recv, g_recv, stash, bgrads, egrads, lacc = carry
                # ---- forward ----
                i = t - s
                fvalid = jnp.logical_and(i >= 0, i < NF)
                ic = jnp.clip(i, 0, NF - 1)
                k = (ic % L) // S
                p = k * S + s
                m = (ic // L) * S + ic % S
                with trace_key_scope(jax.random.fold_in(base_key, m)):
                    h_out, _ = lax.switch(p, branches, blocks, edge,
                                          xs[m], h_recv, ys[m])
                stash = lax.cond(
                    fvalid,
                    lambda st: st.at[k, m % G].set(
                        h_recv.astype(h_aval.dtype)),
                    lambda st: st, stash)

                # ---- backward ----
                j = t - (L + S - 2 - s)
                bvalid = jnp.logical_and(j >= 0, j < NF)
                jc = jnp.clip(j, 0, NF - 1)
                kb = V - 1 - (jc % L) // S
                pb = kb * S + s
                m_b = (jc // L) * S + jc % S
                x_b = stash[kb, m_b % G]

                def f(local, edge, h_in):
                    with trace_key_scope(jax.random.fold_in(base_key, m_b)):
                        return lax.switch(pb, branches, local, edge,
                                          xs[m_b], h_in, ys[m_b])

                (_, l_b), vjp = jax.vjp(f, blocks, edge, x_b)
                bmask = bvalid.astype(jnp.float32)
                seed = (g_recv * bmask.astype(h_aval.dtype), bmask)
                gl, ge, gx = vjp(seed)
                bgrads = jax.tree.map(jnp.add, bgrads, gl)
                egrads = jax.tree.map(jnp.add, egrads, ge)
                lacc = lacc + jnp.where(bvalid, l_b, 0.0)

                h_next = lax.ppermute(h_out, "pp", fwd_perm)
                g_next = lax.ppermute(gx, "pp", bwd_perm)
                return (h_next, g_next, stash, bgrads, egrads, lacc), None

            carry0 = (
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros(h_aval.shape, h_aval.dtype),
                jnp.zeros((V, G) + h_aval.shape, h_aval.dtype),
                jax.tree.map(lambda b: jnp.zeros(b.shape, b.dtype), blocks),
                jax.tree.map(lambda e: jnp.zeros(e.shape, e.dtype), edge),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, bgrads, egrads, lacc), _ = lax.scan(
                tick, carry0, jnp.arange(T))
            # block grads are STAGE-LOCAL: just restore the sharded leading
            # dim — no cross-stage psum (this is the memory win)
            bgrads = jax.tree.map(lambda g: g[None] / M, bgrads)
            # edge grads & loss are replicated-contract: psum assembles
            egrads = jax.tree.map(lambda g: lax.psum(g, "pp") / M, egrads)
            return lax.psum(lacc, "pp") / M, bgrads, egrads

        from jax import shard_map

        fn = shard_map(
            worker, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P("pp"), P()),
            axis_names={"pp"},
            check_vma=False)
        loss, bgrads, egrads = fn(blocks, edge, xs, ys)
        return loss, (bgrads, egrads)

    return grad_fn
