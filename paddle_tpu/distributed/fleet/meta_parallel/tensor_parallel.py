"""TensorParallel model wrapper (reference meta_parallel/tensor_parallel.py:27).

The reference broadcasts mp/dp params at wrap time so every rank starts from
identical weights. Single-controller SPMD has one copy of every logical
param, so consistency is structural; the wrapper's real job here is to
*place* params on the mesh per their PartitionSpecs (shard_params) so the
first jitted step doesn't pay a relayout.
"""
from __future__ import annotations

from .meta_parallel_base import MetaParallelBase


class TensorParallel(MetaParallelBase):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__(layers, hcg, strategy)
        from ..._spmd import shard_params
        from ...topology import get_mesh

        # get_mesh() falls back to a 1-device mesh when none is configured,
        # so placement is a no-op in pure eager single-device runs; real
        # placement errors (bad pspec vs mesh) must surface, not be swallowed
        shard_params(layers, get_mesh())
