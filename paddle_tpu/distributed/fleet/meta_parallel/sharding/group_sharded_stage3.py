"""ZeRO stage-3: parameters themselves live sharded between uses.

Reference: GroupShardedStage3 (meta_parallel/sharding/group_sharded_stage3.py:59)
— per-param segmentation (:362), forward hooks that all-gather a param just
before use and free it after (:497), grads reduce-scattered to the owner.

TPU-native redesign: the hook machinery collapses into placement. Every
param's PartitionSpec gains the ``sharding`` axis, so between jitted steps
the param array is physically scattered (1/N memory per device). Inside the
step XLA's SPMD partitioner inserts the all-gather right before each use
and frees the gathered buffer after — the same gather/free schedule the
reference hand-codes, chosen by the compiler. ``jax.remat`` +
``offload`` compose on top. state_dict still sees full logical tensors
(jax.Arrays are global), so checkpointing needs no stage-3 gather pass
(reference needs explicit get_all_parameters :state_dict hooks)."""
from __future__ import annotations

import jax

from ...._spmd import get_pspec, named_sharding, set_pspec
from ....topology import get_mesh
from ....sharding.sharded_optimizer import shard_optimizer_states, state_pspec
from ..meta_parallel_base import MetaParallelBase

__all__ = ["GroupShardedStage3"]


class GroupShardedStage3(MetaParallelBase):
    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pretrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        self._optimizer = optimizer
        self._offload = offload
        super().__init__(layer, None, None)

    def _prepare_for_model(self):
        mesh = get_mesh()
        deg = int(mesh.shape.get("sharding", 1))
        for _, p in self._layers.named_parameters():
            if deg > 1:
                set_pspec(p, state_pspec(p, mesh))
            # physically scatter now (1/N param memory at rest)
            sh = named_sharding(get_pspec(p) or jax.sharding.PartitionSpec(),
                                mesh)
            try:
                p._value = jax.device_put(p._value, sh)
            except (RuntimeError, ValueError):
                pass  # non-divisible tail params stay replicated
        if self._optimizer is not None:
            shard_optimizer_states(self._optimizer, mesh)

    def get_all_parameters(self, convert2cpu=False):
        """reference stage3 gather API: jax.Arrays are logically global, so
        this is just (optionally host-fetched) passthrough."""
        import numpy as np

        if convert2cpu:
            return [np.asarray(p._value) for p in self._layers.parameters()]
        return list(self._layers.parameters())
