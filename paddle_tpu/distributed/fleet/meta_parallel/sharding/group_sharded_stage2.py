"""ZeRO stage-2: sharded optimizer states + sharded gradients.

Reference: GroupShardedOptimizerStage2
(meta_parallel/sharding/group_sharded_optimizer_stage2.py:53 — per-rank param
partition, post-step broadcast :592) and GroupShardedStage2
(group_sharded_stage2.py:372,409 — per-param grad hooks ``dist.reduce`` to
the owner rank, comm/compute overlap :353).

TPU-native redesign: no hook machinery. Gradients are *annotated* with the
same sharding-axis PartitionSpec as the optimizer state that consumes them;
inside a jitted step XLA then materialises the DP grad sync as
**reduce-scatter** (instead of all-reduce) straight into the shard the
state update reads — which is exactly stage-2's halving of grad traffic
memory. Eagerly (no jit) arrays are global and the wrapper only places
state shards; numerics are identical to DP (the reference's
sharding-vs-DP parity test, hybrid_parallel_sharding_model.py)."""
from __future__ import annotations

from typing import Optional

import jax

from ...._spmd import get_pspec, named_sharding
from ....topology import get_mesh
from ..meta_parallel_base import MetaParallelBase
from ....sharding.sharded_optimizer import shard_optimizer_states, state_pspec

__all__ = ["GroupShardedOptimizerStage2", "GroupShardedStage2"]


class GroupShardedOptimizerStage2:
    """Optimizer wrapper: inner optimizer runs on sharded states.

    reference group_sharded_optimizer_stage2.py:53. ``offload`` keeps states
    on host memory (device_put to CPU), trading step latency for HBM."""

    def __init__(self, params, optim, group=None, offload=False,
                 device="tpu", **kw):
        self._optim = optim
        self._group = group
        self.offload = offload
        mesh = get_mesh()
        shard_optimizer_states(optim, mesh)
        if offload:
            self._host = jax.devices("cpu")[0]

            orig_acc = optim._acc

            def host_acc(name, p, init=None):
                v = orig_acc(name, p, init)
                try:
                    v._value = jax.device_put(v._value, self._host)
                except (RuntimeError, ValueError):
                    pass
                return v

            optim._acc = host_acc

    # delegate the full optimizer surface
    def __getattr__(self, item):
        return getattr(self._optim, item)

    def step(self):
        self._optim.step()

    def clear_grad(self, *a, **kw):
        self._optim.clear_grad(*a, **kw)

    def state_dict(self):
        return self._optim.state_dict()

    def set_state_dict(self, sd):
        return self._optim.set_state_dict(sd)


class GroupShardedStage2(MetaParallelBase):
    """Model wrapper: annotate every param's GRADIENT placement with the
    sharding axis (reference installs per-param reduce hooks; here the
    annotation makes XLA emit reduce-scatter in jitted steps)."""

    def __init__(self, layer, sharding_optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23,
                 auto_refresh_trainable=True, device="tpu", dp_group=None):
        self._sharding_optimizer = sharding_optimizer
        super().__init__(layer, None, None)

    def _prepare_for_model(self):
        from ...._spmd import shard_params

        mesh = get_mesh()
        shard_params(self._layers, mesh)
        for p in self._layers.parameters():
            # grads follow the state spec (sharding axis added)
            p.grad_pspec = state_pspec(p, mesh)

    def grad_specs(self):
        """name → grad PartitionSpec — drop into jit in_shardings for the
        grads pytree of a functional train step."""
        return {name: state_pspec(p, get_mesh())
                for name, p in self._layers.named_parameters()}
