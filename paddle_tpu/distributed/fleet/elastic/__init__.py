"""Elastic training manager (reference: fleet/elastic/manager.py:124).

The reference registers nodes in etcd, heartbeats, and relaunches with a
regenerated rank map when membership changes (watch loop manager.py:120-124,
exit code :30). TPU-native slot: membership rides the native TCPStore (no
etcd in this image) —

- every node heartbeats by bumping the counter ``elastic/hbc/<rank>``
  (counters, not wall-clock stamps: the native store's GET blocks on a
  missing key — rendezvous semantics — while ``add(key, 0)`` reads-or-
  creates without blocking, so the watch loop never wedges on a peer that
  has not come up yet);
- ``watch()`` scans peer heartbeat FRESHNESS: a counter that has not moved
  for ``dead_timeout`` is a dead peer -> RESTART; a bumped ``elastic/join``
  counter is a scale-up -> RESTART; all ranks done -> COMPLETED;
- a RESTART surfaces as :data:`ELASTIC_EXIT_CODE`, which the launcher's
  ``--elastic_level`` loop honors by relaunching every local worker;
- rank regeneration on relaunch is :func:`rendezvous` — a dense rank is
  drawn from a per-generation counter, so survivors of a failure are
  re-admitted with fresh contiguous ranks (the reference rebuilds its rank
  map the same way on membership change);
- state recovery is the sharded-checkpoint restore
  (``distributed/checkpoint``).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

ELASTIC_EXIT_CODE = 101            # manager.py:30
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102

__all__ = ["ElasticManager", "ElasticStatus", "ELASTIC_EXIT_CODE",
           "rendezvous"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def rendezvous(store, generation: int, host: str = "") -> int:
    """Draw a dense rank for this generation (0-based). After a relaunch the
    generation bumps and survivors re-draw contiguous ranks — the
    reference's regenerated rank map on membership change."""
    rank = store.add(f"elastic/gen/{generation}/next_rank", 1) - 1
    if host:
        store.set(f"elastic/gen/{generation}/node/{rank}", host.encode())
    return rank


class ElasticManager:
    """Heartbeat + membership watch over TCPStore (etcd stand-in).

    ``watch()`` is the reference watch-loop body (manager.py:120): it
    returns HOLD while the world is healthy, RESTART when a peer died or
    joined, COMPLETED when every rank reported done.
    """

    def __init__(self, args=None, store=None, np: Optional[int] = None,
                 heartbeat_interval: float = 3.0,
                 dead_timeout: Optional[float] = None,
                 generation: Optional[int] = None):
        self.np = np or int(os.environ.get("PADDLE_ELASTIC_NP", "1") or 1)
        # generation-scoped keys: a relaunched world starts from clean
        # counters instead of inheriting the dead generation's state
        self.generation = generation if generation is not None else int(
            os.environ.get("PADDLE_ELASTIC_GENERATION", "0") or 0)
        self._pre = f"elastic/g{self.generation}"

        self.host = os.environ.get("POD_IP", "127.0.0.1")
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.heartbeat_interval = heartbeat_interval
        # a peer is dead after missing ~3 beats (manager.py watch cadence)
        self.dead_timeout = dead_timeout or heartbeat_interval * 3 + 1.0
        self._store = store
        self._stop = threading.Event()
        self._thread = None
        self.enabled = self._store is not None
        self.need_restart = False
        self._done_marked = False
        self._registered_at = 0.0
        # a peer that NEVER heartbeated is only dead after the assembly
        # grace — slow container starts must not trigger restart loops
        self.assembly_timeout = self.dead_timeout * 10
        # rank -> (last seen beat counter, when it last changed)
        self._beat_seen = {}

    # -- lifecycle ---------------------------------------------------------
    def register(self):
        if not self.enabled:
            return
        self._store.set(f"{self._pre}/node/{self.rank}", self.host.encode())
        self._beat()
        self._store.add(f"{self._pre}/join", 1)
        self._registered_at = time.time()
        self._thread = threading.Thread(target=self._heartbeat, daemon=True)
        self._thread.start()

    def _beat(self):
        self._store.add(f"{self._pre}/hbc/{self.rank}", 1)

    def _heartbeat(self):
        while not self._stop.is_set():
            try:
                self._beat()
            except Exception:
                pass  # store briefly unreachable: next beat retries
            self._stop.wait(self.heartbeat_interval)

    # -- membership --------------------------------------------------------
    def _peer_beats(self, r: int) -> Optional[int]:
        try:
            return int(self._store.add(f"{self._pre}/hbc/{r}", 0))
        except Exception:
            return None

    def dead_peers(self):
        """Ranks whose heartbeat counter has not moved for dead_timeout.
        A rank that never heartbeated (counter 0) counts as dead once the
        local grace period (one dead_timeout after our own registration)
        has passed."""
        now = time.time()
        dead = []
        for r in range(self.np):
            if r == self.rank:
                continue
            beats = self._peer_beats(r)
            if beats is None:
                continue  # store unreachable: no verdict this scan
            if beats == 0:
                # never came up: wait out the assembly grace, not the
                # (much shorter) heartbeat staleness window — a peer whose
                # container starts late must not cause a restart loop
                if now - self._registered_at > self.assembly_timeout:
                    dead.append(r)
                continue
            prev = self._beat_seen.get(r)
            if prev is None or beats != prev[0]:
                self._beat_seen[r] = (beats, now)
            elif now - prev[1] > self.dead_timeout:
                dead.append(r)
        return dead

    def watch(self) -> str:
        """One membership check (the reference's watch loop body :120)."""
        if not self.enabled:
            return ElasticStatus.COMPLETED
        if self.need_restart:
            return ElasticStatus.RESTART
        try:
            done = int(self._store.add(f"{self._pre}/done", 0))
        except Exception:
            done = 0
        if done >= self.np:
            return ElasticStatus.COMPLETED
        # scale-up: more registrations than the expected world size
        # (bring-up joins <= np are normal, not a membership change)
        try:
            join_now = self._store.add(f"{self._pre}/join", 0)
        except Exception:
            join_now = 0
        if join_now > self.np:
            self.need_restart = True
            return ElasticStatus.RESTART
        if self.dead_peers():
            self.need_restart = True
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def signal_restart(self):
        self.need_restart = True

    def mark_done(self):
        """Rank reports clean completion; when all np ranks have, watch()
        returns COMPLETED everywhere. Idempotent per rank (exit() also
        calls it — a double bump would let one rank count twice and flip
        peers to COMPLETED mid-training)."""
        if self.enabled and not self._done_marked:
            self._done_marked = True
            self._store.add(f"{self._pre}/done", 1)

    def exit(self, completed: bool = True):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if completed and self.enabled:
            try:
                self.mark_done()
            except Exception:
                pass
        return 0 if completed else ELASTIC_EXIT_CODE
