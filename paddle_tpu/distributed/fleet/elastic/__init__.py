"""Elastic training manager (reference: fleet/elastic/manager.py:124).

The reference registers nodes in etcd, heartbeats, and relaunches with a
regenerated rank map when membership changes. TPU-native slot: membership
rides the native TCPStore (no etcd in image); scale events surface as the
dedicated exit code the launcher's --elastic_level loop honors, and state
recovery is the sharded-checkpoint restore (distributed/checkpoint).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

ELASTIC_EXIT_CODE = 101            # manager.py:30
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102

__all__ = ["ElasticManager", "ElasticStatus", "ELASTIC_EXIT_CODE"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat + membership watch over TCPStore (etcd stand-in)."""

    def __init__(self, args=None, store=None, np: Optional[int] = None,
                 heartbeat_interval: float = 3.0):
        self.np = np or int(os.environ.get("PADDLE_ELASTIC_NP", "1") or 1)
        self.host = os.environ.get("POD_IP", "127.0.0.1")
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.heartbeat_interval = heartbeat_interval
        self._store = store
        self._stop = threading.Event()
        self._thread = None
        self.enabled = self._store is not None
        self.need_restart = False

    def register(self):
        if not self.enabled:
            return
        self._store.set(f"elastic/node/{self.rank}", self.host.encode())
        self._store.add("elastic/alive", 1)
        self._thread = threading.Thread(target=self._heartbeat, daemon=True)
        self._thread.start()

    def _heartbeat(self):
        while not self._stop.is_set():
            self._store.set(f"elastic/hb/{self.rank}",
                            str(time.time()).encode())
            self._stop.wait(self.heartbeat_interval)

    def watch(self) -> str:
        """One membership check (the reference's watch loop body :120)."""
        if not self.enabled:
            return ElasticStatus.COMPLETED
        if self.need_restart:
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def signal_restart(self):
        self.need_restart = True

    def exit(self, completed: bool = True):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        return 0 if completed else ELASTIC_EXIT_CODE
