from . import (fs, hybrid_parallel_inference,  # noqa: F401
               hybrid_parallel_util, log_util, mix_precision_utils)
from .fs import HDFSClient, LocalFS  # noqa: F401
from .hybrid_parallel_inference import (  # noqa: F401
    HybridParallelInferenceHelper)
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: F401
from .log_util import logger  # noqa: F401
from .mix_precision_utils import (MixPrecisionLayer,  # noqa: F401
                                  MixPrecisionOptimizer, MixPrecisionScaler)


def recompute(function, *args, **kwargs):
    """fleet.utils.recompute parity — activation checkpointing (reference
    fleet/recompute/recompute.py:334)."""
    from ...fleet.recompute import recompute as _rc

    return _rc(function, *args, **kwargs)
