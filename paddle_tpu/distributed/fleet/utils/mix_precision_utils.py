"""Main-grad mixed precision (reference:
fleet/utils/mix_precision_utils.py — MixPrecisionLayer:30 keeps a fp32
``main_grad`` per bf16 param via grad hooks, MixPrecisionOptimizer:93 steps
on it, MixPrecisionScaler:244 unscales into it).

Why it exists: with bf16 params, accumulating gradients across micro-
batches in bf16 loses ~8 mantissa bits; accumulating into an fp32
main_grad keeps the optimizer math exact while compute stays bf16. On TPU
this is the standard bf16-compute/fp32-state recipe; the jitted training
paths (optimizer/functional.adamw_update) already do fp32 math internally,
so this module serves the EAGER (dygraph) path where grads land on
``param.grad`` between backward calls.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ....core.tensor import Tensor

__all__ = ["MixPrecisionLayer", "MixPrecisionOptimizer", "MixPrecisionScaler"]


class MixPrecisionLayer:
    """Wrap a layer whose params run in ``dtype`` (bf16): every backward
    accumulates the fresh grad into ``param.main_grad`` (fp32) via a
    registered grad hook, then clears the low-precision grad reference.

    reference MixPrecisionLayer:30 (its _update_main_grad hook)."""

    def __init__(self, layers, dtype: str = "bfloat16"):
        self._layers = layers
        target = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
        for _, p in layers.named_parameters():
            if jnp.issubdtype(jnp.result_type(p._value), jnp.floating):
                p._value = p._value.astype(target)
            p.main_grad = None

            def hook(g, _p=p):
                gv = g._value if isinstance(g, Tensor) else g
                g32 = gv.astype(jnp.float32)
                if _p.main_grad is None:
                    _p.main_grad = Tensor(g32)
                else:
                    _p.main_grad = Tensor(_p.main_grad._value + g32)
                return g

            p.register_hook(hook)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    __call__ = forward

    def __getattr__(self, item):
        return getattr(self._layers, item)


class MixPrecisionOptimizer:
    """Step on fp32 master weights using main_grad (reference :93):
    maintains a master fp32 copy per bf16 param; at ``step()`` the inner
    optimizer sees (master fp32 param, fp32 main_grad), and the bf16 param
    is refreshed from the updated master."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._masters = {}

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _params(self):
        return list(self._inner._parameter_list or [])

    def step(self):
        swapped = []
        for p in self._params():
            g = getattr(p, "main_grad", None)
            if g is None and p.grad is None:
                continue
            key = id(p)
            master = self._masters.get(key)
            if master is None:
                master = p._value.astype(jnp.float32)
            low_value, low_grad = p._value, p.grad
            p._value = master
            if g is not None:
                p.grad = g
            else:
                gv = low_grad._value if isinstance(low_grad, Tensor) \
                    else low_grad
                p.grad = Tensor(gv.astype(jnp.float32))
            swapped.append((p, key, low_value, low_grad))
        self._inner.step()
        for p, key, low_value, low_grad in swapped:
            self._masters[key] = p._value          # updated fp32 master
            p._value = p._value.astype(low_value.dtype)
            p.grad = low_grad

    def clear_grad(self, set_to_zero: bool = True):
        self._inner.clear_grad()
        for p in self._params():
            p.main_grad = None

    def state_dict(self):
        """Includes the fp32 masters (keyed by param NAME — ids don't
        survive a restart): without them, resume would rebuild masters
        from bf16 params and lose the sub-ulp accumulation this module
        exists to preserve."""
        sd = self._inner.state_dict()
        masters = {}
        for p in self._params():
            m = self._masters.get(id(p))
            if m is not None:
                masters[p.name] = m
        sd["mix_precision_masters"] = masters
        return sd

    def set_state_dict(self, sd):
        masters = sd.pop("mix_precision_masters", None) if isinstance(
            sd, dict) else None
        out = self._inner.set_state_dict(sd)
        if masters:
            by_name = {p.name: p for p in self._params()}
            for name, m in masters.items():
                p = by_name.get(name)
                if p is not None:
                    self._masters[id(p)] = jnp.asarray(m, jnp.float32)
                    p._value = self._masters[id(p)].astype(p._value.dtype)
        return out


class MixPrecisionScaler:
    """GradScaler shim for the main-grad flow (reference :244): bf16 on
    TPU needs no loss scaling (same exponent range as fp32), so scale is
    identity and ``step`` delegates — kept for API compatibility with
    fp16-era training scripts."""

    def __init__(self, scaler=None):
        self._scaler = scaler

    def scale(self, loss):
        return self._scaler.scale(loss) if self._scaler else loss

    def unscale_(self, optimizer):
        if self._scaler:
            self._scaler.unscale_(optimizer)

    def step(self, optimizer):
        optimizer.step()

    def update(self):
        if self._scaler:
            self._scaler.update()
