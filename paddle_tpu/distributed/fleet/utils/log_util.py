"""fleet logger (reference fleet/utils/log_util.py)."""
import logging

logger = logging.getLogger("paddle_tpu.fleet")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logger.addHandler(_h)
logger.setLevel(logging.INFO)


def set_log_level(level):
    logger.setLevel(level)


def get_logger(level=logging.INFO, name="paddle_tpu.fleet"):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    return lg
