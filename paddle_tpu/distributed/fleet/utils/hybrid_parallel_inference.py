"""Hybrid-parallel inference helper (reference:
fleet/utils/hybrid_parallel_inference.py:26 — splits a static program per
pipeline stage (:386), maps params to devices (:369), inserts p2p sends and
a decode while-loop so multi-rank generation runs the program in lockstep).

TPU-native redesign: program surgery collapses into PLACEMENT. One jitted
forward over the hybrid mesh is already the multi-stage program — GSPMD
assigns each weight to its mesh coordinates (the reference's
_update_param_device_map), partitions every op (the _split_program), and
inserts the ICI transfers (the p2p inserts). The decode while-loop is
``lax.while_loop``/``lax.scan`` inside the same program
(inference/generation.py), not a host-driven loop across ranks.

The class keeps the reference's constructor/method surface so fleet
scripts port over; ``wrap_model`` is the TPU-native entry: it places
params according to their TP/PP annotations and returns a jitted sharded
forward.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ...topology import get_mesh

__all__ = ["HybridParallelInferenceHelper"]


class HybridParallelInferenceHelper:
    def __init__(self, startup_program=None, main_program=None,
                 micro_batch_size: int = 1, num_mp: int = 1, num_pp: int = 1,
                 mesh=None, init_comm: bool = True, role_maker=None):
        self._startup = startup_program
        self._main = main_program
        self.micro_batch_size = micro_batch_size
        self.num_mp = num_mp
        self.num_pp = num_pp
        self.mesh = mesh or get_mesh()

    def gen_infer_program(self, sync_in_while_lastpp2firstpp_var_names=None,
                          sync_in_while_var_names=None, debug: bool = False):
        """Reference: rewrites main_program into the per-stage piece with
        p2p + while-loop. Here the recorded Program needs no rewriting —
        the Executor jits it whole and GSPMD partitions it over the mesh —
        so this returns the program unchanged (kept for script parity)."""
        return self._main

    def wrap_model(self, model, donate: bool = False):
        """Place ``model``'s params by their sharding annotations over the
        hybrid mesh and return ``(jitted_forward, sharded_params)``:
        ``jitted_forward(params, *inputs)`` runs the full multi-stage
        forward as ONE SPMD program."""
        from ..._spmd import _filter_spec, get_pspec
        from ....nn.functional_call import functional_call

        mesh = self.mesh
        params = {}
        for name, p in model.named_parameters():
            spec = _filter_spec(get_pspec(p) or P(), mesh)
            params[name] = jax.device_put(
                p.value, NamedSharding(mesh, spec))

        def fwd(pv, *inputs):
            from ....core.tensor import Tensor

            out = functional_call(
                model, pv, *[Tensor(x) if not isinstance(x, Tensor) else x
                             for x in inputs])
            return out.value if hasattr(out, "value") else out

        return jax.jit(fwd, donate_argnums=(0,) if donate else ()), params
