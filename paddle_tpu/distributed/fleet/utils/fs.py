"""Filesystem abstraction (reference: python/paddle/distributed/fleet/
utils/fs.py — FS base, LocalFS, HDFSClient over the hadoop CLI).

LocalFS is fully implemented; HDFSClient shells out to ``hadoop fs`` when a
hadoop binary is available (same mechanism as the reference) and raises a
clear error otherwise.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError", "FSTimeOut"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Reference fs.py LocalFS — local-disk implementation."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in sorted(os.listdir(fs_path)):
            full = os.path.join(fs_path, entry)
            (dirs if os.path.isdir(full) else files).append(entry)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def _rmr(self, fs_path):
        shutil.rmtree(fs_path)

    def _rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            self._rm(fs_path)
        else:
            self._rmr(fs_path)

    def need_upload_download(self) -> bool:
        return False

    def is_file(self, fs_path) -> bool:
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path) -> bool:
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path) -> bool:
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]

    def cat(self, fs_path=None) -> str:
        with open(fs_path) as f:
            return f.read()

    def upload(self, local_path, fs_path):  # local: a copy
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """Reference fs.py HDFSClient — shells out to the hadoop CLI. Every
    operation raises a clear error when no hadoop binary is present (the
    TPU image bundles none)."""

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 time_out: int = 5 * 60 * 1000, sleep_inter: int = 1000):
        self._hadoop = None
        if hadoop_home:
            cand = os.path.join(hadoop_home, "bin", "hadoop")
            if os.path.exists(cand):
                self._hadoop = cand
        elif shutil.which("hadoop"):
            self._hadoop = shutil.which("hadoop")
        self._configs = configs or {}
        self._time_out = time_out

    def _run(self, *args) -> str:
        if self._hadoop is None:
            raise RuntimeError(
                "HDFSClient needs a hadoop CLI (hadoop_home/bin/hadoop); "
                "none found in this image. Use LocalFS, or install hadoop "
                "on the host.")
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=self._time_out / 1000)
        except subprocess.TimeoutExpired as e:
            raise FSTimeOut(
                f"hadoop {' '.join(args)} timed out after "
                f"{self._time_out}ms") from e
        if proc.returncode != 0:
            raise RuntimeError(f"hadoop {' '.join(args)} failed: "
                               f"{proc.stderr[-500:]}")
        return proc.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path) -> bool:
        try:
            self._run("-test", "-e", fs_path)
            return True
        except RuntimeError:
            return False

    def is_dir(self, fs_path) -> bool:
        try:
            self._run("-test", "-d", fs_path)
            return True
        except RuntimeError:
            return False

    def is_file(self, fs_path) -> bool:
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if self.is_exist(fs_dst_path):
            if not overwrite:
                raise FSFileExistsError(fs_dst_path)
            self.delete(fs_dst_path)
        self.rename(fs_src_path, fs_dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None) -> str:
        return self._run("-cat", fs_path)

    def need_upload_download(self) -> bool:
        return True
