"""Hybrid-parallel gradient sync helpers.

Reference: fleet/utils/hybrid_parallel_util.py:227,233
(fused_allreduce_gradients / sharding_reduce_gradients): bucket all grads
and allreduce over the dp (or sharding) group after backward.

TPU-native: in auto/GSPMD context gradients of a data-parallel step are
produced by a psum the compiler already inserted (the batch axis is sharded
over dp), so the eager call is a no-op there; in the eager stacked-ranks
convention it delegates to the collective engine's all_reduce with AVG.
"""
from __future__ import annotations

from ....core.tensor import Tensor
from ...communication.core import in_traced_context

__all__ = ["fused_allreduce_gradients", "sharding_reduce_gradients"]


def fused_allreduce_gradients(parameter_list, hcg=None, group=None):
    from ... import all_reduce
    from ...communication.core import ReduceOp

    axis = "dp"
    if group is not None and getattr(group, "axis_name", None):
        axis = group.axis_name
    if in_traced_context(axis):
        # manual context: psum each grad over dp
        from jax import lax

        for p in parameter_list:
            if p.grad is not None:
                p.grad._inplace_(lax.pmean(p.grad.value, axis))
        return
    # single-controller eager: grads are logically global already (dp batch
    # dim is a sharding of ONE global batch) — nothing to reduce.
    return


def sharding_reduce_gradients(parameter_list, hcg=None):
    """Stage-1/2 grad reduction: same dual-context contract over the
    sharding axis (reference hybrid_parallel_util.py:233)."""
    if in_traced_context("sharding"):
        from jax import lax

        for p in parameter_list:
            if p.grad is not None:
                p.grad._inplace_(lax.pmean(p.grad.value, "sharding"))
    return
