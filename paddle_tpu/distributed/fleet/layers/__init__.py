from . import mpu
