"""TP-correct RNG state tracking.

Reference: fleet/layers/mpu/random.py (RNGStatesTracker:34,
get_rng_state_tracker:84, model_parallel_random_seed:88).

On TPU the hard problem the reference solves (per-mp-rank curand streams so
dropout masks differ across shards but replicate across dp) mostly
disappears: a single functional PRNG key used on a GSPMD-sharded tensor
already yields one consistent *global* mask, each device computing its
shard. The tracker is kept for API parity and for the manual/shard_map
path, where "local" streams fold the mp coordinate into the key.
"""
from __future__ import annotations

import contextlib

import jax

from .....core import random as core_random

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "determinate_seed", "dropout"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    """Named RNG streams (reference random.py:34). Each stream is an
    independent counter-based Generator; ``rng_state(name)`` temporarily
    swaps the default generator so every op in scope draws from it."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self.states_.items()}

    def set_states_tracker(self, states):
        for k, s in states.items():
            self.states_.setdefault(k, core_random.Generator()).set_state(s)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = core_random.Generator(seed)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        orig = core_random.default_generator
        core_random.default_generator = self.states_[name]
        try:
            yield
        finally:
            core_random.default_generator = orig


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    """reference random.py:88 — seed global + local streams. The "local"
    mp-offset stream matters only on the manual path; GSPMD dropout uses one
    global stream."""
    import random as pyrandom

    seed = seed if seed is not None else pyrandom.randint(0, 2**31 - 1)
    global_seed = seed
    local_seed = seed + 1024  # offset stream for shard-local masks
    _RNG_STATE_TRACKER.reset()
    core_random.seed(global_seed)
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)


def determinate_seed(rng_name):
    g = _RNG_STATE_TRACKER.states_.get(rng_name)
    return g.initial_seed() if g else core_random.default_generator.initial_seed()


def dropout(x, p=0.5, axis=None, rng_name=None, training=True,
            mode="upscale_in_train", name=None):
    """Dropout drawing from a named tracker stream (reference random.py
    exposes the same signature)."""
    from .....nn import functional as F

    if rng_name is None:
        return F.dropout(x, p, axis=axis, training=training, mode=mode)
    with get_rng_state_tracker().rng_state(rng_name):
        return F.dropout(x, p, axis=axis, training=training, mode=mode)
