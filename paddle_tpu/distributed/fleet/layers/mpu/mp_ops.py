"""Tensor-parallel communication primitives.

Reference: fleet/layers/mpu/mp_ops.py:26,90,152,218,297,374,664
(_c_identity/_c_concat/_c_split/_mp_allreduce/_c_lookup_table/
_c_softmax_with_cross_entropy/split).

TPU-native dual-context design (same contract as
distributed/communication/core.py):
- **manual context** (inside ``shard_map`` with the mp axis bound): real
  ``lax`` collectives with custom VJPs giving the Megatron f/g conjugate
  pairs (identity-fwd/allreduce-bwd and allreduce-fwd/identity-bwd).
- **auto context** (GSPMD: plain jit over the mesh, or eager): the ops are
  sharding *constraints* — XLA inserts the collectives, and the VJP pairs
  fall out of GSPMD's transpose rules automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .....core.autograd import apply_op
from .....core.tensor import Tensor
from ...._spmd import P, constraint
from ....communication.core import in_traced_context

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "_c_lookup_table", "_c_softmax_with_cross_entropy", "split",
           "mp_axis_name"]

MP_AXIS = "mp"


def mp_axis_name(group=None) -> str:
    if group is not None and getattr(group, "axis_name", None):
        return group.axis_name
    return MP_AXIS


def _manual(axis: str) -> bool:
    """True when the mp axis is bound as a manual (shard_map) axis."""
    return in_traced_context(axis)


# --- f/g conjugate primitives (manual context) -----------------------------

import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _identity_ar_bwd(x, axis: str):
    return x


def _identity_fwd(x, axis):
    return x, None


def _identity_bwd(axis, res, g):
    return (lax.psum(g, axis),)


_identity_ar_bwd.defvjp(_identity_fwd, _identity_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _allreduce_id_bwd(x, axis: str):
    return lax.psum(x, axis)


def _ar_fwd(x, axis):
    return lax.psum(x, axis), None


def _ar_bwd(axis, res, g):
    return (g,)


_allreduce_id_bwd.defvjp(_ar_fwd, _ar_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _split_gather_bwd(x, axis: str):
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    blk = x.shape[-1] // n
    return lax.dynamic_slice_in_dim(x, idx * blk, blk, axis=x.ndim - 1)


def _split_fwd(x, axis):
    return _split_gather_bwd(x, axis), x.shape[-1]


def _split_bwd(axis, full_dim, g):
    # cotangent of a replicated input: every rank contributes its own block —
    # zero-pad to the full dim and psum (≡ the reference's c_allgather bwd)
    idx = lax.axis_index(axis)
    blk = g.shape[-1]
    padded = jnp.zeros(g.shape[:-1] + (full_dim,), g.dtype)
    padded = lax.dynamic_update_slice_in_dim(padded, g, idx * blk,
                                             axis=g.ndim - 1)
    return (lax.psum(padded, axis),)


_split_gather_bwd.defvjp(_split_fwd, _split_bwd)


# --- public ops ------------------------------------------------------------

def _c_identity(tensor, group=None):
    """Fwd identity / bwd allreduce over mp (Megatron "f").
    reference mp_ops.py:26. In auto context GSPMD's transpose generates the
    backward psum from the sharded consumers, so this is a pass-through."""
    axis = mp_axis_name(group)
    if _manual(axis):
        return apply_op(lambda v: _identity_ar_bwd(v, axis), tensor,
                        op_name="c_identity")
    return tensor


def _mp_allreduce(tensor, group=None, use_calc_stream=True, use_model_parallel=True):
    """Fwd allreduce / bwd identity over mp (Megatron "g").
    reference mp_ops.py:218. Auto context: a replicated-sharding constraint —
    XLA materialises the psum when producers are mp-partial."""
    axis = mp_axis_name(group)
    if _manual(axis):
        return apply_op(lambda v: _allreduce_id_bwd(v, axis), tensor,
                        op_name="mp_allreduce")
    # auto/GSPMD: partial-sums are already resolved by the compiler at use
    # sites; nothing to do eagerly.
    return tensor


def _c_split(tensor, group=None):
    """Keep this rank's slice of the last dim. reference mp_ops.py:152.
    Manual: dynamic-slice by axis_index (bwd = all_gather via custom vjp
    falls out of slice transpose + psum; we use explicit collective).
    Auto: a sharding constraint putting the last dim on mp."""
    axis = mp_axis_name(group)
    if _manual(axis):
        return apply_op(lambda v: _split_gather_bwd(v, axis), tensor,
                        op_name="c_split")
    nd = tensor.ndim if hasattr(tensor, "ndim") else jnp.ndim(tensor)
    return constraint(tensor, P(*([None] * (nd - 1) + [MP_AXIS])))


def _c_concat(tensor, group=None):
    """All-gather along the last dim. reference mp_ops.py:90.
    Auto: replicate-constraint on the last dim."""
    axis = mp_axis_name(group)
    if _manual(axis):
        def f(v):
            return lax.all_gather(v, axis, axis=v.ndim - 1, tiled=True)

        return apply_op(f, tensor, op_name="c_concat")
    nd = tensor.ndim if hasattr(tensor, "ndim") else jnp.ndim(tensor)
    return constraint(tensor, P(*([None] * nd)))


def _c_lookup_table(table, index, start_index=0, vocab_size=-1, name=None, group=None):
    """Vocab-parallel embedding lookup (reference mp_ops.py:297 →
    c_embedding_op.cu). Manual context: mask ids outside the local vocab
    shard, lookup locally, psum partial rows. Auto: plain take — GSPMD
    shards the gather along the vocab dim of the table."""
    axis = mp_axis_name(group)
    if _manual(axis):
        def f(tbl, idx):
            rank = lax.axis_index(axis)
            per = tbl.shape[0]
            local = idx - rank * per
            ok = (local >= 0) & (local < per)
            safe = jnp.where(ok, local, 0)
            out = jnp.take(tbl, safe, axis=0)
            out = jnp.where(ok[..., None], out, 0.0).astype(tbl.dtype)
            return lax.psum(out, axis)

        return apply_op(f, table, index, op_name="c_lookup_table")

    def f(tbl, idx):
        return jnp.take(tbl, idx, axis=0)

    return apply_op(f, table, index, op_name="c_lookup_table")


def _c_softmax_with_cross_entropy(logits, label, group=None, ignore_index=-100,
                                  return_softmax=False):
    """Class-parallel softmax cross entropy (reference mp_ops.py:374 →
    c_softmax_with_cross_entropy_op.cu): logits' class dim is sharded over
    mp; global max/sum ride the mp axis.

    Manual context: explicit pmax/psum reductions over the local class shard.
    Auto: numerically-identical global math; GSPMD partitions the reductions.
    """
    axis = mp_axis_name(group)
    if _manual(axis):
        def f(lg, lb):
            rank = lax.axis_index(axis)
            per = lg.shape[-1]
            ignored = lb == ignore_index
            gmax = lax.pmax(jnp.max(lg, axis=-1, keepdims=True), axis)
            ex = jnp.exp(lg - gmax)
            gsum = lax.psum(jnp.sum(ex, axis=-1, keepdims=True), axis)
            # local logit of the target class (0 when not on this shard)
            local = lb - rank * per
            ok = (local >= 0) & (local < per) & ~ignored
            safe = jnp.where(ok, local, 0)
            picked = jnp.take_along_axis(lg, safe[..., None], axis=-1)
            picked = jnp.where(ok[..., None], picked, 0.0)
            tgt = lax.psum(picked, axis)
            loss = (jnp.log(gsum) + gmax - tgt)
            loss = jnp.where(ignored[..., None], 0.0, loss)
            soft = ex / gsum
            return (loss, soft) if return_softmax else loss

        out = apply_op(f, logits, label, op_name="c_softmax_with_cross_entropy")
        return out

    def f(lg, lb):
        ignored = lb == ignore_index
        safe_lb = jnp.where(ignored, 0, lb)
        gmax = jnp.max(lg, axis=-1, keepdims=True)
        ex = jnp.exp(lg - gmax)
        gsum = jnp.sum(ex, axis=-1, keepdims=True)
        idx = safe_lb[..., None] if safe_lb.ndim < lg.ndim else safe_lb
        tgt = jnp.take_along_axis(lg, idx, axis=-1)
        loss = jnp.log(gsum) + gmax - tgt
        loss = jnp.where(ignored[..., None] if ignored.ndim < loss.ndim else ignored,
                         0.0, loss)
        soft = ex / gsum
        return (loss, soft) if return_softmax else loss

    return apply_op(f, logits, label, op_name="c_softmax_with_cross_entropy")


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference mp_ops.py:664 — builds a parallel linear/embedding layer.
    Kept for API parity; delegates to the mpu layer classes."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unsupported operation {operation}")
