"""Megatron-style tensor-parallel layers, TPU-native.

Reference: fleet/layers/mpu/mp_layers.py:35,173,343,524
(VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear /
ParallelCrossEntropy).

Design departure from the reference (deliberate, TPU-first): the reference
constructs PER-RANK weight shards (each process allocates out_features/n) and
calls explicit collectives. Here every layer holds the FULL logical weight
annotated with a ``PartitionSpec`` on the ``mp`` mesh axis; under jit over
the mesh GSPMD places shards and inserts the psums (scaling-book recipe).
The same layer also runs correctly inside ``shard_map`` (manual collectives
via mp_ops) and eagerly on one device — one definition, three contexts.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn import functional as F
from .....nn.layer.layers import Layer
from ...._spmd import P, constraint, set_pspec
from ....topology import axis_size
from . import mp_ops

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


class VocabParallelEmbedding(Layer):
    """Embedding with the vocabulary dim sharded over mp.
    reference mp_layers.py:35; lookup semantics of c_embedding_op.cu."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.mp_group = mp_group
        self.world_size = (mp_group.nranks if mp_group is not None
                           else axis_size("mp"))
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError(
                f"num_embeddings {num_embeddings} must be divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            dtype=self._dtype)
        set_pspec(self.weight, P("mp", None))

    def forward(self, x):
        # _c_lookup_table already completes the psum in the manual path and
        # is a full gather in the auto path — no extra allreduce.
        return mp_ops._c_lookup_table(self.weight, x, group=self.mp_group)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}, mp={self.world_size}"


class ColumnParallelLinear(Layer):
    """Linear with the OUTPUT dim sharded over mp (weight columns).
    reference mp_layers.py:173. fwd: y = f(x) @ W, f = identity-fwd /
    allreduce-bwd; output stays mp-sharded unless gather_output."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.mp_group = mp_group
        self.world_size = (mp_group.nranks if mp_group is not None
                           else axis_size("mp"))
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} must be divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            dtype=self._dtype)
        set_pspec(self.weight, P(None, "mp"))
        self.has_bias = has_bias if has_bias is not None else True
        if self.has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True,
                dtype=self._dtype)
            set_pspec(self.bias, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        x = mp_ops._c_identity(x, group=self.mp_group)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = mp_ops._c_concat(out, group=self.mp_group)
        else:
            nd = out.ndim
            out = constraint(out, P(*([None] * (nd - 1) + ["mp"])))
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the INPUT dim sharded over mp (weight rows).
    reference mp_layers.py:343. fwd: y = g(x_parallel @ W) + b, g =
    allreduce-fwd / identity-bwd; bias added AFTER the reduce (replicated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.mp_group = mp_group
        self.world_size = (mp_group.nranks if mp_group is not None
                           else axis_size("mp"))
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} must be divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            dtype=self._dtype)
        set_pspec(self.weight, P("mp", None))
        self.has_bias = has_bias
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True,
                dtype=self._dtype)
            set_pspec(self.bias, P(None))
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = mp_ops._c_split(x, group=self.mp_group)
        else:
            nd = x.ndim
            x = constraint(x, P(*([None] * (nd - 1) + ["mp"])))
        out = F.linear(x, self.weight, None)
        out = mp_ops._mp_allreduce(out, group=self.mp_group)
        nd = out.ndim
        out = constraint(out, P(*([None] * nd)))
        if self.bias is not None:
            out = out + self.bias
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, input_is_parallel={self.input_is_parallel}")


class ParallelCrossEntropy(Layer):
    """Softmax cross entropy over class-dim-sharded logits.
    reference mp_layers.py:524."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.mp_group = mp_group
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = mp_ops._c_softmax_with_cross_entropy(
            input, label, group=self.mp_group, ignore_index=self.ignore_index)
        return loss
