"""Activation recomputation (gradient checkpointing).

Reference: fleet/recompute/recompute.py:69,334,458 — RecomputeFunction
PyLayer that reruns forward during backward, replaying RNG state so dropout
masks match (use_reentrant, preserve_rng_state options).

TPU-native: ``jax.checkpoint`` (remat) IS this feature — XLA drops the
activations and re-derives them in the backward pass. RNG replay is
structural: randomness comes from explicit keys, and the recompute scope
captures the keys drawn in the first trace, so the rematerialised forward
reuses identical keys by construction (no state save/restore dance).
"""
from __future__ import annotations

from typing import Sequence

import jax

from ....core.autograd import apply_op
from ....core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs):
    """reference recompute.py:334 parity. Wraps `function(*args)` so its
    activations are rematerialised during backward.

    When `function` is a Layer, its parameters are threaded through the
    autograd tape explicitly (via functional_call substitution) — closure-
    captured weights would otherwise be constants of the remat trace and
    receive NO gradient under eager ``backward()``.
    """
    is_layer = hasattr(function, "named_parameters")
    if is_layer:
        param_items = list(function.named_parameters())
    else:
        param_items = []
    names = [k for k, _ in param_items]
    ptensors = [p for _, p in param_items]
    np_ = len(ptensors)

    def _wrap(v):
        # Only array-likes become Tensor views: None / python scalars /
        # flags must keep their identity, or `arg is None` branches inside
        # `function` flip (a Tensor(None) attn_mask silently rerouted llama
        # attention off the flash kernel onto the S²-materialising SDPA
        # path under remat).
        if not isinstance(v, Tensor) and hasattr(v, "shape"):
            return Tensor(v, stop_gradient=False)
        return v

    def pure(*vals):
        pvals, rest = vals[:np_], [_wrap(v) for v in vals[np_:]]
        if is_layer:
            from ....nn.functional_call import functional_call

            return functional_call(function, dict(zip(names, pvals)),
                                   *rest, **kwargs)
        out = function(*rest, **kwargs)
        return jax.tree.map(
            lambda o: o.value if isinstance(o, Tensor) else o, out,
            is_leaf=lambda o: isinstance(o, Tensor))

    remat_fn = jax.checkpoint(pure)
    return apply_op(remat_fn, *ptensors, *args, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference recompute.py:458 — recompute over a Sequential in segments.
    ctx: {'segments': int} or int."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else int(ctx)
    if hasattr(functions, "children"):
        layers = list(functions.children())
    else:
        layers = list(functions)
    if segments <= 0:
        segments = 1
    seg_size = max(1, len(layers) // segments)

    from ....nn.layer.container import Sequential

    x = args[0]
    i = 0
    while i < len(layers):
        # a Sequential view over the segment so recompute() sees a Layer and
        # threads the segment's parameters through the tape (a plain closure
        # would capture them as remat constants → no grads under backward())
        seg = Sequential(*layers[i:i + seg_size])
        x = recompute(seg, x, **kwargs)
        i += seg_size
    return x
