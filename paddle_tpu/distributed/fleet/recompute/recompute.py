"""Activation recomputation (gradient checkpointing).

Reference: fleet/recompute/recompute.py:69,334,458 — RecomputeFunction
PyLayer that reruns forward during backward, replaying RNG state so dropout
masks match (use_reentrant, preserve_rng_state options).

TPU-native: ``jax.checkpoint`` (remat) IS this feature — XLA drops the
activations and re-derives them in the backward pass. RNG replay is
structural: randomness comes from explicit keys, and the recompute scope
captures the keys drawn in the first trace, so the rematerialised forward
reuses identical keys by construction (no state save/restore dance).
"""
from __future__ import annotations

from typing import Sequence

import jax

from ....core.autograd import apply_op
from ....core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs):
    """reference recompute.py:334 parity. Wraps `function(*args)` so its
    activations are rematerialised during backward."""
    from ....core import random as core_random

    # Draw one key now: the traced function folds it for any dropout inside,
    # and remat replays the identical fold (keys are data, not state).
    def fn(*tensor_args):
        return function(*tensor_args, **kwargs)

    def pure(*vals):
        # rebuild Tensor views so user `function` (written against the eager
        # API) runs under the remat trace
        wrapped = [Tensor(v, stop_gradient=False) if not isinstance(v, Tensor)
                   else v for v in vals]
        out = fn(*wrapped)
        if isinstance(out, Tensor):
            return out.value
        if isinstance(out, (tuple, list)):
            return type(out)(o.value if isinstance(o, Tensor) else o for o in out)
        return out

    remat_fn = jax.checkpoint(pure)
    return apply_op(remat_fn, *args, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference recompute.py:458 — recompute over a Sequential in segments.
    ctx: {'segments': int} or int."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else int(ctx)
    if hasattr(functions, "children"):
        layers = list(functions.children())
    else:
        layers = list(functions)
    if segments <= 0:
        segments = 1
    seg_size = max(1, len(layers) // segments)

    def run_segment(seg):
        def f(x):
            for l in seg:
                x = l(x)
            return x

        return f

    x = args[0]
    i = 0
    while i < len(layers):
        seg = layers[i:i + seg_size]
        x = recompute(run_segment(seg), x, **kwargs)
        i += seg_size
    return x
