"""Search algorithms over the pruned candidate grid.

Reference analog: python/paddle/distributed/auto_tuner/search.py
(SearchAlgo:22, GridSearch:38).
"""
from __future__ import annotations

from abc import ABC, abstractmethod

from .prune import _PRUNE_FUNC
from .utils import search_all

__all__ = ["SearchAlgo", "GridSearch"]


class SearchAlgo(ABC):
    def __init__(self, tuner_cfg):
        self.tuner_cfg = tuner_cfg

    @abstractmethod
    def search_once(self, history_cfgs):
        ...

    def prune(self, tuner_cfg, cur_cfg, history_cfgs):
        return any(f(tuner_cfg, cur_cfg, history_cfgs)
                   for f in _PRUNE_FUNC)


class GridSearch(SearchAlgo):
    """Exhaustive walk over the promise-ordered grid, skipping pruned."""

    def __init__(self, tuner_cfg):
        super().__init__(tuner_cfg)
        self.idx = 0
        self.all_tasks = search_all(tuner_cfg)

    def search_once(self, history_cfgs):
        while self.idx < len(self.all_tasks):
            cfg = self.all_tasks[self.idx]
            self.idx += 1
            if not self.prune(self.tuner_cfg, cfg, history_cfgs):
                return dict(cfg)
        return None
