"""Trial launcher: run one candidate config as an isolated subprocess.

Reference analog: the auto-tuner handing each candidate to the distributed
launcher and reading metrics back from logs
(python/paddle/distributed/auto_tuner/utils.py: gen_new_args /
read_metric_log). TPU-native: the subprocess bootstraps a virtual CPU mesh
of ``num_devices`` when the host doesn't expose that many real chips
(exactly like ``__graft_entry__.dryrun_multichip``), so the full dp×mp×pp×
sharding search space is explorable on a single host; on a real pod slice
the same code path uses the real devices.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, Optional

__all__ = ["run_trial"]


def run_trial(cur_cfg: Dict, tuner_cfg: Dict,
              timeout: Optional[float] = None) -> Dict:
    """Run one candidate; returns the trial's metric record (merged over
    the candidate dict). ``run_cmd`` in tuner_cfg overrides the built-in
    trial module (it must print one JSON line on stdout)."""
    from .utils import num_devices

    n = num_devices(tuner_cfg)
    trial = dict(cur_cfg)
    trial["model_cfg"] = tuner_cfg.get("model_cfg", {})
    trial["steps"] = tuner_cfg.get("steps_per_trial", 3)

    env = dict(os.environ)
    env["PADDLE_AUTO_TUNER_TRIAL"] = json.dumps(trial)

    # real devices only on explicit request: probing jax.devices() here
    # would initialize (and hold) the accelerator runtime in the tuner
    # parent, locking the chips away from every trial subprocess
    use_real = bool(tuner_cfg.get("use_real_devices", False))
    if not use_real:
        env["JAX_PLATFORMS"] = "cpu"
        env["PADDLE_AUTO_TUNER_FORCE_CPU"] = "1"
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()

    cmd = tuner_cfg.get("run_cmd") or [
        sys.executable, "-m", "paddle_tpu.distributed.auto_tuner.trial"]
    timeout = timeout or tuner_cfg.get("trial_timeout", 600)
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return {**cur_cfg, "error": "timeout"}

    rec = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            break
        except (json.JSONDecodeError, ValueError):
            continue
    if rec is None:
        err = (proc.stderr or "")[-400:]
        kind = ("oom" if ("RESOURCE_EXHAUSTED" in err or
                          "Out of memory" in err) else "error")
        rec = {"error": kind, "detail": err}
    return {**cur_cfg, **rec}
