"""Trial-history recorder with CSV persistence.

Reference analog: python/paddle/distributed/auto_tuner/recorder.py
(History_recorder:22) — csv module instead of pandas.
"""
from __future__ import annotations

import csv
import os
from typing import Optional, Tuple

__all__ = ["HistoryRecorder", "History_recorder"]


def _from_csv(v):
    """CSV stores strings; metrics must come back numeric or sort_metric
    would compare lexicographically ("9.0" > "100.0")."""
    if v is None or v == "":
        return None
    for conv in (int, float):
        try:
            return conv(v)
        except (TypeError, ValueError):
            continue
    return v


class HistoryRecorder:
    def __init__(self) -> None:
        self.history = []
        self.store_path: Optional[str] = None

    def add_cfg(self, **kwargs):
        self.history.append(dict(kwargs))

    def sort_metric(self, direction, metric_name) -> None:
        reverse = direction == "Maximize"
        bad = float("-inf") if reverse else float("inf")
        self.history.sort(
            key=lambda x: x.get(metric_name) if x.get(metric_name) is not None
            else bad,
            reverse=reverse)

    def get_best(self, metric, direction) -> Tuple[Optional[dict], bool]:
        """Returns (best_cfg, err). err=True when there is nothing usable."""
        self.sort_metric(direction=direction, metric_name=metric)
        if not self.history or self.history[0].get(metric) is None:
            return None, True
        return self.history[0], False

    def store_history(self, path="./history.csv"):
        self.store_path = path
        keys = []
        for rec in self.history:
            for k in rec:
                if k not in keys:
                    keys.append(k)
        if "job_id" in keys:  # reference puts job_id first
            keys.insert(0, keys.pop(keys.index("job_id")))
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for rec in self.history:
                w.writerow(rec)

    def load_history(self, path="./history.csv") -> Tuple[list, bool]:
        if self.store_path is None:
            self.store_path = path
        if not os.path.exists(self.store_path):
            return self.history, True
        with open(self.store_path, newline="") as f:
            self.history = [
                {k: _from_csv(v) for k, v in r.items()}
                for r in csv.DictReader(f)]
        return self.history, False


History_recorder = HistoryRecorder  # reference-compatible alias
