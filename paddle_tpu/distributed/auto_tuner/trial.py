"""Built-in auto-tuner trial target: one hybrid-parallel llama train step.

Launched by ``runner.run_trial`` as a subprocess with the candidate config
JSON in ``PADDLE_AUTO_TUNER_TRIAL``. Builds the dp×mp×pp×sharding mesh the
candidate describes, jits the training step, times ``steps`` global
batches and prints ONE JSON line with the metrics. TPU-native counterpart
of the reference auto-tuner's launched training job (the reference launches
a user script through the distributed launcher and greps its logs —
python/paddle/distributed/auto_tuner/utils.py:read_metric_log; here the
trial is a process that *reports* its metric instead of being grepped).
"""
from __future__ import annotations

import json
import os
import sys
import time


def main():
    if os.environ.get("PADDLE_AUTO_TUNER_FORCE_CPU"):
        # sitecustomize may pin jax_platforms at interpreter start; the
        # config API wins over it (same bootstrap as dryrun_multichip)
        import jax

        jax.config.update("jax_platforms", "cpu")
    cfg = json.loads(os.environ["PADDLE_AUTO_TUNER_TRIAL"])
    try:
        rec = _run(cfg)
    except Exception as e:  # noqa: BLE001 — classify, report, exit clean
        msg = str(e)
        kind = ("oom" if ("RESOURCE_EXHAUSTED" in msg or
                          "Out of memory" in msg or "OOM" in msg)
                else "error")
        rec = {"error": kind, "detail": msg[:400]}
    print(json.dumps(rec), flush=True)


def _run(cfg):
    import jax
    import numpy as np

    import paddle_tpu as paddle  # noqa: F401
    from paddle_tpu.distributed.topology import build_mesh, set_mesh
    from paddle_tpu.models import LlamaForCausalLM, llama_config

    model_cfg = cfg.get("model_cfg", {})
    dp = int(cfg.get("dp_degree", 1))
    mp = int(cfg.get("mp_degree", 1))
    pp = int(cfg.get("pp_degree", 1))
    shd = int(cfg.get("sharding_degree", 1))
    mbs = int(cfg.get("micro_batch_size", 1))
    recompute = cfg.get("use_recompute", "none")
    gbs = int(model_cfg.get("global_batch_size", mbs * dp * shd))
    seq = int(model_cfg.get("seq_len", 64))
    steps = int(cfg.get("steps", 3))
    acc = max(1, gbs // (mbs * dp * shd))

    preset = model_cfg.get("preset", "tiny")
    over = {k: model_cfg[k] for k in
            ("hidden_size", "intermediate_size", "num_hidden_layers",
             "num_attention_heads", "num_key_value_heads", "vocab_size",
             "dtype") if k in model_cfg}
    if recompute not in ("none", "full"):
        # the Layer-model trial has no "dots" checkpoint policy; erroring
        # keeps the record honest instead of measuring full and calling
        # it dots (llama_functional carries the dots policy)
        raise NotImplementedError(
            f"built-in trial supports use_recompute none/full, got "
            f"{recompute!r}")
    if recompute == "full":
        over["recompute"] = "full"
    lcfg = llama_config(preset, **over)

    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    if pp > 1:
        tps, loss = _run_pp(lcfg, dp * shd, pp, mp, mbs, acc, seq, steps, rng)
    else:
        tps, loss = _run_flat(lcfg, dp, mp, shd, mbs, acc, seq, steps, rng)
    wall = time.perf_counter() - t0
    return {"tokens_per_sec": round(tps, 2), "final_loss": loss,
            "wall_s": round(wall, 2), "acc_steps": acc}


def _run_flat(lcfg, dp, mp, shd, mbs, acc, seq, steps, rng):
    """dp×mp×sharding pjit step (pp folded out); grad-accumulate acc×."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed._spmd import _filter_spec, get_pspec
    from paddle_tpu.distributed.sharding.sharded_optimizer import state_pspec
    from paddle_tpu.distributed.topology import build_mesh, set_mesh
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.nn.functional_call import functional_call
    from paddle_tpu.optimizer.functional import (adamw_init, adamw_update,
                                                 clip_by_global_norm)

    mesh = build_mesh(dp=dp, sharding=shd, mp=mp)
    set_mesh(mesh)
    model = LlamaForCausalLM(lcfg)
    params = {k: p.value for k, p in model.named_parameters()}
    pspecs = {k: _filter_spec(get_pspec(p) or P(), mesh)
              for k, p in model.named_parameters()}
    mspecs = {k: _filter_spec(state_pspec(p, mesh), mesh)
              for k, p in model.named_parameters()}
    params = {k: jax.device_put(v, NamedSharding(mesh, pspecs[k]))
              for k, v in params.items()}
    opt_state = adamw_init(params)
    opt_state = opt_state._replace(
        m={k: jax.device_put(v, NamedSharding(mesh, mspecs[k]))
           for k, v in opt_state.m.items()},
        v={k: jax.device_put(v, NamedSharding(mesh, mspecs[k]))
           for k, v in opt_state.v.items()})

    def loss_fn(pv, ids, labels):
        return functional_call(model, pv, paddle.Tensor(ids),
                               paddle.Tensor(labels))

    batch_sh = NamedSharding(mesh, P(None, ("dp", "sharding"), None))

    def train_step(pv, st, ids, labels):
        # ids/labels: [acc, B, S] — grad-accumulate over the leading axis
        def micro(c, xy):
            g_acc, l_acc = c
            l, g = jax.value_and_grad(loss_fn)(pv, xy[0], xy[1])
            return (jax.tree.map(jnp.add, g_acc, g), l_acc + l), None

        zero = jax.tree.map(jnp.zeros_like, pv)
        (grads, ls), _ = jax.lax.scan(micro, (zero, jnp.zeros(())),
                                      (ids, labels))
        n = ids.shape[0]
        grads = jax.tree.map(lambda g: g / n, grads)
        grads, _ = clip_by_global_norm(grads, 1.0)
        st, pv = adamw_update(grads, st, pv, lr=1e-4)
        return pv, st, ls / n

    # params/state are already committed with their target shardings;
    # jit infers in/out shardings from the args (explicit in_shardings +
    # donation without out_shardings trips the alias-sharding check)
    jitted = jax.jit(train_step, donate_argnums=(0, 1))

    b = mbs * dp * shd
    ids = rng.randint(0, lcfg.vocab_size, (acc, b, seq)).astype(np.int32)
    labels = rng.randint(0, lcfg.vocab_size, (acc, b, seq)).astype(np.int32)
    ids = jax.device_put(ids, batch_sh)
    labels = jax.device_put(labels, batch_sh)
    params, opt_state, loss = jitted(params, opt_state, ids, labels)
    _ = float(loss)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = jitted(params, opt_state, ids, labels)
    lv = float(loss)
    dt = time.perf_counter() - t0
    return acc * b * seq * steps / dt, lv


def _run_pp(lcfg, dp, pp, mp, mbs, acc, seq, steps, rng):
    """pp×dp compiled 1F1B pipeline over llama decoder stages."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
                                                            PipelineLayer)
    from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import (
        build_pipeline_train_step)
    from paddle_tpu.distributed.topology import build_mesh, set_mesh
    from paddle_tpu.models.llama import (LlamaDecoderLayer, _rope_cos_sin)

    mesh = build_mesh(pp=pp, dp=dp, mp=mp)
    set_mesh(mesh)

    cos, sin = _rope_cos_sin(seq, lcfg.head_dim, lcfg.rope_theta,
                             paddle.float32)

    class Embed(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(lcfg.vocab_size, lcfg.hidden_size)

        def forward(self, ids):
            return self.emb(ids)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.layer = LlamaDecoderLayer(lcfg)

        def forward(self, x):
            return self.layer(x, paddle.Tensor(cos), paddle.Tensor(sin))

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(lcfg.hidden_size, lcfg.vocab_size,
                                bias_attr=False)

        def forward(self, x):
            return self.fc(x)

    def loss_fn(out, y):
        return nn.functional.cross_entropy(
            out.reshape([-1, lcfg.vocab_size]), y.reshape([-1]))

    descs = ([LayerDesc(Embed)]
             + [LayerDesc(Block) for _ in range(lcfg.num_hidden_layers)]
             + [LayerDesc(Head)])
    pipe = PipelineLayer(descs, num_stages=pp, loss_fn=loss_fn)
    params = {k: p.value for k, p in pipe.named_parameters()}
    step, init = build_pipeline_train_step(pipe, accumulate_steps=acc,
                                           mesh=mesh, lr=1e-4)
    st = init(params)
    b = mbs * acc * dp
    ids = rng.randint(0, lcfg.vocab_size, (b, seq)).astype(np.int32)
    y = rng.randint(0, lcfg.vocab_size, (b, seq)).astype(np.int32)
    params, st, loss = step(params, st, ids, y)
    _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, st, loss = step(params, st, ids, y)
    lv = float(loss)
    dt = time.perf_counter() - t0
    return b * seq * steps / dt, lv


if __name__ == "__main__":
    main()
