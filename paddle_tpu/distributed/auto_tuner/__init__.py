"""Auto-tuner: automatic search over hybrid-parallel launch configs.

Reference analog: python/paddle/distributed/auto_tuner/ (tuner.py:19).
Searches {dp, mp, pp, sharding(+stage), micro_batch_size, recompute} with
grid search + prune rules, runs each surviving candidate as a real trial
(subprocess over a virtual or real device mesh), records tokens/sec per
config and returns the best.

    from paddle_tpu.distributed.auto_tuner import tune
    best = tune({"num_devices": 8,
                 "model_cfg": {"preset": "tiny", "global_batch_size": 8,
                               "seq_len": 64}})
"""
from .prune import register_prune, same_cfgs_beside
from .recorder import History_recorder, HistoryRecorder
from .runner import run_trial
from .search import GridSearch, SearchAlgo
from .tuner import AutoTuner, tune
from .utils import default_candidates, search_all

__all__ = ["AutoTuner", "tune", "run_trial", "GridSearch", "SearchAlgo",
           "HistoryRecorder", "History_recorder", "default_candidates",
           "search_all", "register_prune", "same_cfgs_beside"]
