"""Candidate generation / enumeration for the auto-tuner.

Reference analog: python/paddle/distributed/auto_tuner/utils.py
(default_candidates:27, search_all:129). TPU-native differences: degrees
factor a `jax.sharding.Mesh` instead of process ranks, "sharding" means the
ZeRO axis of the mesh, and recompute is the jax.checkpoint policy of the
scan body ("none" | "dots" | "full") rather than per-op recompute lists.
"""
from __future__ import annotations

import itertools
from typing import Dict, List

__all__ = ["default_candidates", "search_all", "divisors", "num_devices"]

# "dots" (save-matmul-outputs checkpoint policy, llama_functional) is a
# valid explicit candidate but not a default: the built-in Layer-model
# trial only supports none/full, and a mislabeled trial is worse than a
# smaller default grid.
RECOMPUTE_CANDIDATES = ["none", "full"]


def num_devices(tuner_cfg: Dict) -> int:
    """The device count every stage (grid, prune, trial env) agrees on."""
    return int(tuner_cfg.get("num_devices", tuner_cfg.get("num_gpus", 8)))


def divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg: Dict) -> Dict[str, list]:
    """Build the candidate lists for every tunable knob.

    "auto" (or absence) expands to all divisors of the device count for
    degree knobs; an explicit list passes through; a scalar becomes a
    single-candidate list.
    """
    n = num_devices(tuner_cfg)
    cands: Dict[str, list] = {}

    def _degree(key):
        v = tuner_cfg.get(key, "auto")
        if v == "auto" or v is None:
            return divisors(n)
        if isinstance(v, (list, tuple)):
            return [int(x) for x in v]
        return [int(v)]

    for key in ("dp_degree", "mp_degree", "pp_degree", "sharding_degree"):
        cands[key] = _degree(key)

    v = tuner_cfg.get("micro_batch_size", "auto")
    gbs = int(tuner_cfg.get("model_cfg", {}).get("global_batch_size", 8))
    if v == "auto" or v is None:
        cands["micro_batch_size"] = divisors(gbs)
    elif isinstance(v, (list, tuple)):
        cands["micro_batch_size"] = [int(x) for x in v]
    else:
        cands["micro_batch_size"] = [int(v)]

    v = tuner_cfg.get("sharding_stage", "auto")
    cands["sharding_stage"] = ([1, 2, 3] if v in ("auto", None)
                               else v if isinstance(v, (list, tuple))
                               else [int(v)])

    v = tuner_cfg.get("use_recompute", "auto")
    if v in ("auto", None):
        cands["use_recompute"] = list(RECOMPUTE_CANDIDATES)
    elif isinstance(v, (list, tuple)):
        cands["use_recompute"] = list(v)
    elif isinstance(v, bool):
        cands["use_recompute"] = ["full" if v else "none"]
    else:
        cands["use_recompute"] = [str(v)]
    return cands


def search_all(tuner_cfg: Dict) -> List[Dict]:
    """Cartesian product of all candidates, ordered most-promising-first:
    smaller mp (less comm) before larger, larger micro-batch before smaller
    (better MXU shapes), no-recompute before full (faster when it fits)."""
    cands = tuner_cfg["candidates"]
    keys = ["dp_degree", "mp_degree", "pp_degree", "sharding_degree",
            "sharding_stage", "micro_batch_size", "use_recompute"]
    all_cfgs = [dict(zip(keys, vals))
                for vals in itertools.product(*(cands[k] for k in keys))]

    rc_rank = {"none": 0, "dots": 1, "full": 2}
    all_cfgs.sort(key=lambda c: (
        c["mp_degree"], c["pp_degree"], c["sharding_degree"],
        c["sharding_stage"], -c["micro_batch_size"],
        rc_rank.get(c["use_recompute"], 3)))
    return all_cfgs
