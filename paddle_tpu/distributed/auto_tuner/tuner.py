"""AutoTuner: grid/prune search over hybrid-parallel configs.

Reference analog: python/paddle/distributed/auto_tuner/tuner.py:19
(AutoTuner.search_once loop driven by the launcher). TPU-native: `tune()`
closes the whole loop in-process — search_once → run_trial (subprocess on a
virtual or real mesh) → record — and returns the best config by the target
metric.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .recorder import HistoryRecorder
from .search import GridSearch
from .utils import default_candidates

__all__ = ["AutoTuner", "tune"]


class AutoTuner:
    def __init__(self, tuner_cfg: Dict):
        self.cur_task_id = 1
        self.task_limit = tuner_cfg.get("task_limit", 100)
        tuner_cfg = dict(tuner_cfg)
        tuner_cfg["candidates"] = default_candidates(tuner_cfg)
        search_algo = tuner_cfg.get("search_algo", "grid")
        if search_algo == "grid":
            self.algo = GridSearch(tuner_cfg)
        else:
            raise NotImplementedError(
                f"search_algo {search_algo!r} (only 'grid')")
        self.tuner_cfg = tuner_cfg
        self.history_cfgs = []

    def search_once(self) -> Optional[Dict]:
        """Next un-pruned candidate, or None when the space is exhausted."""
        if self.cur_task_id > self.task_limit:
            return None
        new_cfg = self.algo.search_once(self.history_cfgs)
        if new_cfg is None:
            return None
        self.cur_task_id += 1
        self.history_cfgs.append(new_cfg)
        return new_cfg

    def add_cfg(self, cfg: Dict):
        """Feed a trial result back so history-based prunes see it."""
        for h in self.history_cfgs:
            if all(h.get(k) == cfg.get(k) for k in h):
                h.update(cfg)
                return
        self.history_cfgs.append(cfg)


def tune(tuner_cfg: Dict,
         run_fn: Optional[Callable[[Dict], Dict]] = None,
         history_csv: Optional[str] = None) -> Optional[Dict]:
    """Full search loop. ``run_fn(cfg) -> metrics`` overrides the built-in
    subprocess runner (useful for tests / custom models). Returns the best
    record by ``metric`` (default tokens_per_sec, maximized)."""
    from .runner import run_trial

    tuner = AutoTuner(tuner_cfg)
    recorder = HistoryRecorder()
    metric = tuner_cfg.get("metric", "tokens_per_sec")
    direction = tuner_cfg.get("direction", "Maximize")
    job_id = 0
    while True:
        cfg = tuner.search_once()
        if cfg is None:
            break
        job_id += 1
        rec = (run_fn(cfg) if run_fn is not None
               else run_trial(cfg, tuner.tuner_cfg))
        rec = {**cfg, **rec, "job_id": job_id}
        tuner.add_cfg(rec)
        recorder.add_cfg(**rec)
    if history_csv:
        recorder.store_history(history_csv)
    best, err = recorder.get_best(metric, direction)
    return None if err else best
