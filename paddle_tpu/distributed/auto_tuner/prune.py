"""Prune rules for the auto-tuner search space.

Reference analog: python/paddle/distributed/auto_tuner/prune.py
(_PRUNE_FUNC registry, prune_by_mp:47, prune_by_pp:84, prune_by_mbs:117).
Each rule returns True when the candidate config should be skipped. The
history-based rule prunes configs dominated by an already-observed OOM
(same parallelism, smaller or equal memory footprint succeeded/failed).
"""
from __future__ import annotations

from typing import Dict, List, Optional

_PRUNE_FUNC = []

__all__ = ["register_prune", "prune_by_mp", "prune_by_pp", "prune_by_mbs",
           "prune_by_sharding", "prune_by_degree_product",
           "prune_by_memory_history", "same_cfgs_beside", "_PRUNE_FUNC"]


def register_prune(func):
    _PRUNE_FUNC.append(func)
    return func


def same_cfgs_beside(attr: str, cur_cfg: Dict,
                     history_cfgs: List[Dict]) -> List[Dict]:
    """History configs identical to cur_cfg except for `attr`."""
    results = []
    for cfg in history_cfgs:
        if all(cfg.get(k) == v for k, v in cur_cfg.items() if k != attr):
            results.append(cfg)
    return results


@register_prune
def prune_by_degree_product(tuner_cfg, cur_cfg, history_cfgs=None):
    """dp*mp*pp*sharding must exactly factor the device count."""
    from .utils import num_devices

    n = num_devices(tuner_cfg)
    prod = (cur_cfg["dp_degree"] * cur_cfg["mp_degree"]
            * cur_cfg["pp_degree"] * cur_cfg["sharding_degree"])
    return prod != n


@register_prune
def prune_by_mp(tuner_cfg, cur_cfg, history_cfgs=None):
    """hidden/vocab/num_heads must split evenly over mp; mp <= 8 default."""
    mp = cur_cfg.get("mp_degree")
    if not mp:
        return False
    model_cfg = tuner_cfg.get("model_cfg", {})
    hidden = model_cfg.get("hidden_size")
    vocab = model_cfg.get("vocab_size")
    heads = model_cfg.get("num_attention_heads")
    if hidden and hidden % mp != 0:
        return True
    if vocab and vocab % mp != 0:
        return True
    if heads and heads % mp != 0:
        return True
    return mp > 8


@register_prune
def prune_by_pp(tuner_cfg, cur_cfg, history_cfgs=None):
    """layers must split evenly over pp stages; microbatch count must cover
    the pipeline (acc_steps >= pp for a full 1F1B schedule)."""
    pp = cur_cfg.get("pp_degree")
    if not pp:
        return False
    model_cfg = tuner_cfg.get("model_cfg", {})
    layers = model_cfg.get("num_layers")
    if layers and layers % pp != 0:
        return True
    gbs = model_cfg.get("global_batch_size")
    mbs = cur_cfg.get("micro_batch_size")
    dp = cur_cfg.get("dp_degree", 1) * cur_cfg.get("sharding_degree", 1)
    if gbs and mbs and pp > 1:
        acc = gbs // (mbs * dp)
        if acc < pp:
            return True
    return False


@register_prune
def prune_by_mbs(tuner_cfg, cur_cfg, history_cfgs=None):
    """micro_batch_size must divide the per-replica batch."""
    gbs = tuner_cfg.get("model_cfg", {}).get("global_batch_size")
    mbs = cur_cfg.get("micro_batch_size")
    if not (gbs and mbs):
        return False
    dp = cur_cfg.get("dp_degree", 1) * cur_cfg.get("sharding_degree", 1)
    if gbs % dp != 0:
        return True
    local = gbs // dp
    return local % mbs != 0


@register_prune
def prune_by_sharding(tuner_cfg, cur_cfg, history_cfgs=None):
    """stage>1 needs an actual sharding axis; stage must be 1/2/3."""
    stage = cur_cfg.get("sharding_stage", 1)
    deg = cur_cfg.get("sharding_degree", 1)
    if stage not in (1, 2, 3):
        return True
    if deg == 1 and stage != 1:
        return True
    return False


@register_prune
def prune_by_memory_history(tuner_cfg, cur_cfg, history_cfgs=None):
    """If an identical config except a SMALLER micro_batch_size (or lighter
    recompute) already OOMed, this one will too — skip without running."""
    if not history_cfgs:
        return False
    rc_rank = {"none": 0, "dots": 1, "full": 2}
    for prev in same_cfgs_beside("micro_batch_size", cur_cfg, history_cfgs):
        if prev.get("error") == "oom" and \
                prev["micro_batch_size"] <= cur_cfg["micro_batch_size"]:
            return True
    for prev in same_cfgs_beside("use_recompute", cur_cfg, history_cfgs):
        if prev.get("error") == "oom" and \
                rc_rank.get(prev.get("use_recompute"), 0) >= \
                rc_rank.get(cur_cfg.get("use_recompute"), 0):
            return True
    return False
