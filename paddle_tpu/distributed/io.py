"""paddle.distributed.io parity (reference: python/paddle/distributed/
io.py — persistable save/load for static programs; the PS-table branches
of the reference collapse per DESIGN.md's descope).
"""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable",
           "load_inference_model_distributed"]


def is_persistable(var) -> bool:
    """Parameters and long-lived buffers are persistable (reference
    io.py:355 checks the var's persistable flag)."""
    from ..nn.parameter import Parameter

    return isinstance(var, Parameter) or bool(
        getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save a static program's parameters (reference io.py:386). The
    distributed-PS branch (_save_distributed_persistables) is descoped;
    the dense path maps to framework.io.save of the program params."""
    from ..framework.io import save
    from ..static.program import default_main_program

    prog = main_program or default_main_program()
    state = {name: p for name, p in prog.param_objs.items()}
    os.makedirs(dirname, exist_ok=True)
    save(state, os.path.join(dirname, filename or "__params__.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    """Inverse of save_persistables (reference io.py:131)."""
    from ..framework.io import load
    from ..static.program import default_main_program, global_scope

    prog = main_program or default_main_program()
    state = load(os.path.join(dirname, filename or "__params__.pdparams"))
    scope = global_scope()
    for name, v in state.items():
        if name in prog.param_objs:
            val = v.value if hasattr(v, "value") else v
            prog.param_objs[name].set_value(val)
            scope.set(name, prog.param_objs[name]._value)


def load_inference_model_distributed(dirname, executor, model_filename=None,
                                     params_filename=None):
    """reference io.py:458 — non-PS path == static.load_inference_model."""
    from ..static import load_inference_model

    return load_inference_model(dirname, executor)
