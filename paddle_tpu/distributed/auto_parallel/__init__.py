"""Auto-parallel (semi-automatic SPMD) — reference:
python/paddle/distributed/auto_parallel/ (ProcessMesh, shard_tensor
interface.py, Engine static/engine.py:854, Strategy strategy.py).

TPU-native collapse (SURVEY.md §3.6): the reference's
Completer→Partitioner→Resharder pipeline IS XLA's GSPMD propagation —
the user marks a few placements (shard_tensor), jit compiles ONE program
over the mesh, and the compiler completes/partitions/reshards. The Engine
keeps the reference's fit/evaluate/predict surface on top of a donated,
fully-jitted train step.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor
from .._spmd import get_pspec, set_pspec
from ..topology import get_mesh, set_mesh

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_op", "reshard", "dtensor_from_fn", "Strategy", "Engine",
           "to_static"]


class ProcessMesh:
    """reference auto_parallel/process_mesh.py — an N-D logical device mesh
    with named dims; backed by a jax.sharding.Mesh."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._shape = list(arr.shape)
        self._process_ids = arr.flatten().tolist()
        devices = np.asarray(jax.devices())
        if devices.size < arr.size:
            raise ValueError(
                f"ProcessMesh needs {arr.size} devices, have {devices.size}")
        picked = devices[np.asarray(self._process_ids)]
        self._jax_mesh = Mesh(picked.reshape(arr.shape),
                              axis_names=tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def mesh(self) -> Mesh:
        return self._jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


class Placement:
    pass


class Shard(Placement):
    """Shard tensor dim `dim` across the corresponding mesh dim."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending-reduction placement; jit materialises the psum on use."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return "Partial()"


def _placements_to_spec(placements: Sequence[Placement], pm: ProcessMesh,
                        ndim: int) -> P:
    spec: List[Optional[str]] = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            if spec[pl.dim] is not None:
                raise ValueError(f"tensor dim {pl.dim} sharded twice")
            spec[pl.dim] = pm.dim_names[mesh_dim]
    return P(*spec)


def shard_tensor(x, process_mesh: ProcessMesh, placements,
                 dtype=None, stop_gradient=None):
    """reference interface.py shard_tensor: place x on the mesh per
    `placements` (one per MESH dim)."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    spec = _placements_to_spec(placements, process_mesh, t.ndim)
    set_pspec(t, spec)
    sh = NamedSharding(process_mesh.mesh, spec)
    try:
        t._value = jax.device_put(t._value, sh)
    except (RuntimeError, ValueError):
        pass  # abstract/tracer values keep the annotation only
    return t


def shard_op(op, process_mesh: ProcessMesh, in_placements=None,
             out_placements=None):
    """reference interface.py shard_op — returns a wrapped op whose outputs
    get sharding constraints."""

    def wrapped(*args, **kwargs):
        out = op(*args, **kwargs)
        if out_placements:
            from .._spmd import constraint

            spec = _placements_to_spec(out_placements, process_mesh,
                                       out.ndim)
            out = constraint(out, spec, process_mesh.mesh)
        return out

    return wrapped


def reshard(x, process_mesh: ProcessMesh, placements):
    """Explicit placement change (reference reshard API): device_put with
    the new sharding — XLA emits the collective."""
    return shard_tensor(x, process_mesh, placements)


def dtensor_from_fn(fn, process_mesh: ProcessMesh, placements, *args,
                    **kwargs):
    return shard_tensor(fn(*args, **kwargs), process_mesh, placements)


class Strategy:
    """reference auto_parallel/strategy.py — typed config tree."""

    class _Cfg(dict):
        __getattr__ = dict.get

        def __setattr__(self, k, v):
            self[k] = v

    def __init__(self, config=None):
        self.auto_mode = "semi"
        self.amp = Strategy._Cfg(enable=False, dtype="bfloat16", level="O1")
        self.recompute = Strategy._Cfg(enable=False)
        self.sharding = Strategy._Cfg(enable=False, degree=1, stage=1)
        self.pipeline = Strategy._Cfg(enable=False, schedule_mode="1F1B",
                                      accumulate_steps=1)
        self.gradient_merge = Strategy._Cfg(enable=False, k_steps=1)
        if config:
            for k, v in dict(config).items():
                setattr(self, k, v)


class Engine:
    """reference static/engine.py:854 — fit/evaluate/predict over ONE jitted
    SPMD step."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy: Optional[Strategy] = None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics
        self._strategy = strategy or Strategy()
        self._step_fn = None
        self._eval_fn = None
        self._predict_fn = None
        self._params = None
        self._opt_state = None
        self.history: List[float] = []

    # -- build --------------------------------------------------------------
    def _build(self):
        from ...nn.functional_call import functional_call

        model, loss_fn = self._model, self._loss
        mesh = get_mesh()
        self._params = {k: p.value for k, p in model.named_parameters()}
        # place params per their annotations (shard_tensor/set_pspec marks)
        from .._spmd import named_sharding

        for k, p in model.named_parameters():
            spec = get_pspec(p)
            if spec is not None:
                self._params[k] = jax.device_put(
                    self._params[k], named_sharding(spec, mesh))

        remat = self._strategy.recompute.enable
        accum = int(self._strategy.pipeline.accumulate_steps or 1)

        def loss_of(params, x, y):
            def fwd(x, y):
                out = functional_call(model, params, Tensor(x))
                l = loss_fn(Tensor(out), Tensor(y))
                lv = l._value if isinstance(l, Tensor) else l
                return jnp.mean(lv)

            f = jax.checkpoint(fwd) if remat else fwd
            if accum > 1:
                xs = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
                ys = y.reshape((accum, y.shape[0] // accum) + y.shape[1:])
                tot, _ = jax.lax.scan(
                    lambda c, xy: (c + f(xy[0], xy[1]), None),
                    jnp.zeros((), jnp.float32), (xs, ys))
                return tot / accum
            return f(x, y)

        opt = self._optimizer

        def step(params, opt_state, x, y, lr):
            loss, grads = jax.value_and_grad(loss_of)(params, x, y)
            new_params, opt_state = opt._static_update(
                params, grads, opt_state, lr=lr)
            return new_params, opt_state, loss

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))
        self._eval_fn = jax.jit(loss_of)

    def prepare(self, *a, **kw):
        if self._step_fn is None:
            self._build()
        return self

    # -- loops --------------------------------------------------------------
    def fit(self, train_data, epochs: int = 1, batch_size: int = 32,
            steps_per_epoch=None, valid_data=None, log_freq: int = 10,
            verbose: int = 1, **kw):
        from ...io import DataLoader, Dataset

        if self._step_fn is None:
            self._build()
        loader = (train_data if not isinstance(train_data, Dataset)
                  else DataLoader(train_data, batch_size=batch_size,
                                  shuffle=True))
        for epoch in range(epochs):
            for step_i, batch in enumerate(loader):
                if steps_per_epoch and step_i >= steps_per_epoch:
                    break
                x, y = batch
                xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
                yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
                lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
                self._params, self._opt_state, loss = self._step_fn(
                    self._params, self._opt_state, xv, yv, lr)
                self.history.append(float(loss))
                if verbose and step_i % log_freq == 0:
                    print(f"[AutoParallel] epoch {epoch} step {step_i} "
                          f"loss {float(loss):.4f}")
        # write trained params back into the model (eager view)
        for k, p in self._model.named_parameters():
            p._value = self._params[k]
        return self.history

    def evaluate(self, eval_data, batch_size: int = 32, **kw):
        from ...io import DataLoader, Dataset

        if self._eval_fn is None:
            self._build()
        loader = (eval_data if not isinstance(eval_data, Dataset)
                  else DataLoader(eval_data, batch_size=batch_size))
        losses = []
        for batch in loader:
            x, y = batch
            xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
            yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
            losses.append(float(self._eval_fn(self._params, xv, yv)))
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, batch_size: int = 32, **kw):
        from ...io import DataLoader, Dataset
        from ...nn.functional_call import functional_call

        loader = (test_data if not isinstance(test_data, Dataset)
                  else DataLoader(test_data, batch_size=batch_size))
        params = self._params or {
            k: p.value for k, p in self._model.named_parameters()}
        # one forward program per Engine, not per predict() call: a
        # fresh jax.jit wrapper owns a fresh trace cache, so rebuilding
        # it here re-traced (and for new batch shapes re-compiled) the
        # model on EVERY call (PT001)
        if self._predict_fn is None:
            self._predict_fn = jax.jit(lambda p, x: functional_call(
                self._model, p, Tensor(x)))
        fn = self._predict_fn
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
            outs.append(np.asarray(fn(params, xv)))
        return outs


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """reference auto_parallel to_static helper — returns a prepared Engine."""
    e = Engine(model=layer, loss=loss, optimizer=optimizer, strategy=strategy)
    return e.prepare()
