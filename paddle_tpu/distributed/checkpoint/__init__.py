"""Distributed (sharded, mesh-aware) checkpointing.

Reference: per-rank state dicts + conversion tooling
(incubate/distributed/utils/io/dist_save.py, dist_load.py, save_for_auto.py;
fleet/utils/pp_parallel_adaptor.py re-partitions PP checkpoints;
sharding stage-3 gathers params on save).

TPU-native redesign: checkpoints are written from GLOBAL jax.Arrays through
orbax/tensorstore — each host writes only the shards it owns, and load
RESHARDS automatically to whatever mesh/PartitionSpec the restore target
uses. The whole adaptor/gather machinery collapses: TP×PP×ZeRO →
any-new-mesh conversion is just "load with different target shardings".
``state_dict`` keys are preserved verbatim for weight portability.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor
from .._spmd import get_pspec, named_sharding
from ..topology import get_mesh

__all__ = ["save_state_dict", "load_state_dict", "reshard_state_dict"]


def _to_raw(sd: Dict[str, Any]):
    return {k: (v._value if isinstance(v, Tensor) else v)
            for k, v in sd.items()}


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    """Write a (possibly sharded) state dict (reference
    paddle.distributed.save_state_dict). Values may live scattered on the
    mesh; tensorstore streams each host's shards."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _to_raw(state_dict), force=True)
    ckptr.wait_until_finished()


def load_state_dict(path: str, state_dict: Optional[Dict[str, Any]] = None,
                    process_group=None, shardings: Optional[Dict] = None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False) -> Dict[str, Any]:
    """Restore (reference paddle.distributed.load_state_dict). If
    ``state_dict`` is given its entries define the target structure AND
    placement (each tensor's current pspec/sharding); values are restored
    IN PLACE and resharded as needed. Otherwise returns plain arrays."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if state_dict is None:
        restored = ckptr.restore(path)
        return {k: Tensor(v) for k, v in restored.items()}

    mesh = get_mesh()
    targets = {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        spec = get_pspec(v) if isinstance(v, Tensor) else None
        if spec is not None:
            sh = named_sharding(spec, mesh)
        else:
            sh = getattr(val, "sharding", None)
        targets[k] = jax.ShapeDtypeStruct(
            tuple(np.shape(val)), val.dtype if hasattr(val, "dtype")
            else np.asarray(val).dtype, sharding=sh)
    restored = ckptr.restore(path, targets)
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            v._value = restored[k]
        else:
            state_dict[k] = restored[k]
    return state_dict


def reshard_state_dict(state_dict: Dict[str, Any],
                       specs: Dict[str, Any], mesh=None) -> Dict[str, Any]:
    """Re-place every entry per `specs` (name → PartitionSpec) on `mesh` —
    the TP×PP×ZeRO → new-layout conversion (reference
    pp_parallel_adaptor.py / save_for_auto.py) as a pure placement op on
    global arrays."""
    mesh = mesh or get_mesh()
    out = {}
    for k, v in state_dict.items():
        val = v._value if isinstance(v, Tensor) else v
        spec = specs.get(k)
        if spec is None:
            out[k] = v
            continue
        placed = jax.device_put(val, named_sharding(spec, mesh))
        out[k] = Tensor(placed) if isinstance(v, Tensor) else placed
    return out
